"""Dictionary-encoded device columns — compressed execution.

BENCH_r05 measured roofline_frac ~ 0.006 behind a 0.11 GB/s
host->device link; ROADMAP item 2 names the lever: move fewer bytes by
executing over compressed, device-resident data ("GPU Acceleration of
SQL Analytics on Compressed Data", PAPERS.md). This module makes
dictionary encoding a first-class device representation:

- A `DeviceColumn` whose `encoding` slot holds a `DeviceDictionary` is
  ENCODED: `data` is a [cap] vector of narrow integer codes and the
  dictionary itself (a padded string byte-matrix + lengths) lives in a
  separate, deduplicated device allocation. The link carries codes
  (2-4 B/row) instead of padded value bytes; a 2000-entry string
  dimension crosses once as a dictionary, not 36M decoded rows.
- Dictionaries are interned by CONTENT: the same parquet dictionary
  appearing in many row groups / shuffle blocks maps to one `dict_id`
  (a content digest, stable across processes) and one device upload,
  charged to the SpillCatalog's reservation ledger.
- Decode is DEFERRED to the last operator that needs materialized
  values: `decode_column` is an HBM-local gather (trace-safe), and the
  D2H collect path decodes host-side from the fetched codes+dictionary
  so the link never carries decoded strings at all.
- Operators lower onto codes where value semantics allow it:
  equality/IN/null predicates probe the host dictionary and compare
  codes (`encoded_equality`); group-by keys group on codes (interned
  dictionaries have unique values, so code equality == value
  equality) and ride the sort-free binned-aggregation path via the
  stamped [0, K) vrange; equi-join keys rewrite to `CodesOf` when both
  sides are encoded — dictionary identity is checked and a mismatched
  side RE-ENCODES through a host remap table instead of decoding.

Null handling is normalized at intern time (the one dictionary-null
discipline both upload paths share): a null VALUE inside the arrow
dictionary folds into row validity, and duplicate values collapse to
one canonical code — so code comparisons are always value-exact.
"""

from __future__ import annotations

import hashlib
import os
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import pyarrow as pa

from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import StringType
from spark_rapids_tpu.sqltypes.datatypes import integer as _int_type

#: codes narrower than this dictionary size ship as int16
_INT16_MAX_K = 1 << 15
#: host-side dictionaries retained for predicate probes / remaps
_HOST_KEEP = 512


class DeviceDictionary:
    """Device-resident dictionary shared by every column encoded with
    it: `data` [K, max_bytes] uint8 padded value matrix, `lengths` [K]
    int32. `dict_id` (the content digest) rides in the pytree aux, so
    jax retraces — and the fused engine re-keys — per distinct
    dictionary, which is what makes trace-time host probes of the
    dictionary safe to bake into compiled programs."""

    __slots__ = ("data", "lengths", "dict_id")

    def __init__(self, data, lengths, dict_id: str):
        self.data = data
        self.lengths = lengths
        self.dict_id = dict_id

    @property
    def num_values(self) -> int:
        return int(self.data.shape[0])

    def size_bytes(self) -> int:
        return (self.data.size * self.data.dtype.itemsize
                + self.lengths.size * 4)

    def _tree_flatten(self):
        return (self.data, self.lengths), self.dict_id

    @classmethod
    def _tree_unflatten(cls, dict_id, children):
        data, lengths = children
        return cls(data, lengths, dict_id)


jax.tree_util.register_pytree_node(
    DeviceDictionary,
    lambda d: d._tree_flatten(),
    DeviceDictionary._tree_unflatten,
)


class _HostDict:
    """Host-side view of one interned dictionary: the padded matrix the
    device copy was built from, the value->code index for predicate
    probes, and the canonical pyarrow values for re-emitting
    DictionaryArrays at the shuffle boundary."""

    __slots__ = ("matrix", "lengths", "values", "index", "nbytes")

    def __init__(self, matrix: np.ndarray, lengths: np.ndarray,
                 values: pa.Array):
        self.matrix = matrix
        self.lengths = lengths
        self.values = values
        self.index: Dict[str, int] = {
            v: i for i, v in enumerate(values.to_pylist())}
        self.nbytes = matrix.nbytes + lengths.nbytes


_lock = threading.Lock()
_host_dicts: "OrderedDict[str, _HostDict]" = OrderedDict()
_device_dicts: "OrderedDict[str, Tuple[DeviceDictionary, int]]" = \
    OrderedDict()
_device_pid: Optional[int] = None


def enabled() -> bool:
    """spark.rapids.tpu.encoded.enabled of the active session (default
    on; sessionless callers — tests driving the bridge directly — get
    the default)."""
    from spark_rapids_tpu.config import rapids_conf as rc

    try:
        from spark_rapids_tpu.api.session import TpuSparkSession

        s = TpuSparkSession.active()
        if s is not None:
            return bool(s.rapids_conf.get(rc.ENCODED_ENABLED))
    except Exception:
        pass
    return bool(rc.ENCODED_ENABLED.default)


def _conf_int(entry) -> int:
    try:
        from spark_rapids_tpu.api.session import TpuSparkSession

        s = TpuSparkSession.active()
        if s is not None:
            return int(s.rapids_conf.get(entry))
    except Exception:
        pass
    return int(entry.default)


def max_dictionary_rows() -> int:
    from spark_rapids_tpu.config import rapids_conf as rc

    return _conf_int(rc.ENCODED_MAX_DICT_ROWS)


def dictionary_decode(arr: pa.Array) -> pa.Array:
    """THE host-side dictionary decode both upload paths share
    (arrow_bridge.column_from_arrow and fused.upload_narrowed used to
    carry their own copies): index-nulls AND null values inside the
    dictionary both land as result nulls, one discipline for both."""
    if pa.types.is_dictionary(arr.type):
        arr = arr.dictionary_decode()
    return arr


# ------------------------------------------------------------- interning

def _digest(values: pa.Array) -> str:
    h = hashlib.sha1()
    for v in values.to_pylist():
        if v is None:
            h.update(b"\x01N")
        else:
            b = v.encode("utf-8")
            h.update(len(b).to_bytes(4, "little"))
            h.update(b)
    return h.hexdigest()[:20]


def intern_dictionary(values: pa.Array
                      ) -> Tuple[str, Optional[np.ndarray]]:
    """Intern one arrow dictionary VALUES array; returns (dict_id,
    remap) where remap maps raw code -> canonical code (-1 for codes
    whose value is null), or None when the dictionary was already
    canonical (unique, no nulls). Canonicalization is what makes code
    equality == value equality everywhere downstream."""
    pv = values.to_pylist()
    seen: Dict[str, int] = {}
    canon: List[str] = []
    remap = np.empty(max(len(pv), 1), dtype=np.int32)
    dirty = False
    for i, v in enumerate(pv):
        if v is None:
            remap[i] = -1
            dirty = True
            continue
        j = seen.get(v)
        if j is None:
            j = seen[v] = len(canon)
            canon.append(v)
        else:
            dirty = True
        remap[i] = j
    cvals = pa.array(canon, type=pa.large_string())
    dict_id = _digest(cvals)
    with _lock:
        hd = _host_dicts.get(dict_id)
    if hd is None:
        from spark_rapids_tpu.columnar.arrow_bridge import \
            _string_to_matrix

        if len(cvals):
            matrix, lengths = _string_to_matrix(cvals)
        else:
            # empty dictionary: one zero row keeps decode gathers and
            # program shapes well-formed (no code ever references it)
            matrix = np.zeros((1, 8), np.uint8)
            lengths = np.zeros(1, np.int32)
        hd = _HostDict(matrix, lengths, cvals)
        with _lock:
            _host_dicts[dict_id] = hd
            _host_dicts.move_to_end(dict_id)
            while len(_host_dicts) > _HOST_KEEP:
                _host_dicts.popitem(last=False)
    return dict_id, (remap[:len(pv)] if dirty else None)


def _host_dict(dict_id: str) -> Optional[_HostDict]:
    with _lock:
        hd = _host_dicts.get(dict_id)
        if hd is not None:
            _host_dicts.move_to_end(dict_id)
        return hd


def device_dictionary(dict_id: str) -> Optional[DeviceDictionary]:
    """Device copy of an interned dictionary, uploaded ONCE per
    distinct content and charged to the SpillCatalog's reservation
    ledger; returns None (caller falls back to decoded upload) when
    the dictionary is unknown or the reservation fails."""
    global _device_pid
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.obs import telemetry
    from spark_rapids_tpu.runtime.errors import (
        TpuRetryOOM,
        TpuSplitAndRetryOOM,
    )
    from spark_rapids_tpu.runtime.memory import get_catalog

    pid = os.getpid()
    with _lock:
        if _device_pid != pid:
            # forked worker: inherited device arrays/reservations
            # belong to the parent — start a fresh cache (same rule as
            # the obs bus post-fork reinstall)
            _device_dicts.clear()
            _device_pid = pid
        cached = _device_dicts.get(dict_id)
        if cached is not None:
            _device_dicts.move_to_end(dict_id)
            return cached[0]
    hd = _host_dict(dict_id)
    if hd is None:
        return None
    nbytes = hd.nbytes
    catalog = get_catalog()
    try:
        catalog.reserve(nbytes, tag="encoded.dict", query_id=0)
    except (TpuRetryOOM, TpuSplitAndRetryOOM):
        return None
    dd = DeviceDictionary(
        telemetry.ledgered_put(jnp.asarray(hd.matrix),
                               "encoded.dictUpload"),
        jnp.asarray(hd.lengths), dict_id)
    budget = _conf_int(rc.ENCODED_DICT_CACHE_BYTES)
    with _lock:
        _device_dicts[dict_id] = (dd, nbytes)
        _device_dicts.move_to_end(dict_id)
        total = sum(b for _, b in _device_dicts.values())
        while total > budget and len(_device_dicts) > 1:
            _, (_, old_bytes) = _device_dicts.popitem(last=False)
            catalog.release(old_bytes, query_id=0)
            total -= old_bytes
    return dd


def dictionary_values(dict_id: str) -> Optional[pa.Array]:
    hd = _host_dict(dict_id)
    return None if hd is None else hd.values


def probe_code(dict_id: str, value: Optional[str]) -> Optional[int]:
    """Host-side dictionary probe: the canonical code of `value`, or
    None when the value is absent (or null, or the dictionary is no
    longer retained)."""
    if value is None:
        return None
    hd = _host_dict(dict_id)
    if hd is None:
        return None
    return hd.index.get(value)


def remap_table(src_id: str, dst_id: str) -> Optional[np.ndarray]:
    """[K_src] int32 mapping src code -> dst code (-1 when the value is
    absent from dst) — the re-encode fallback for joins over
    identity-mismatched dictionaries."""
    if src_id == dst_id:
        return None
    src = _host_dict(src_id)
    dst = _host_dict(dst_id)
    if src is None or dst is None:
        return None
    out = np.full(max(len(src.index), 1), -1, dtype=np.int32)
    for v, c in src.index.items():
        out[c] = dst.index.get(v, -1)
    return out


# --------------------------------------------------- column construction

def encoded_column_from_arrow(arr: pa.Array, field, cap: int):
    """pa.DictionaryArray -> encoded DeviceColumn (numpy code leaves,
    device dictionary handle), or None when encoding does not apply
    (non-string values, disabled, oversized dictionary, failed device
    reservation) — the caller then decodes through
    `dictionary_decode` and uploads plain."""
    if not isinstance(field.dataType, StringType):
        return None
    if not enabled():
        return None
    values = arr.dictionary
    if len(values) > max_dictionary_rows():
        return None
    dict_id, remap = intern_dictionary(values)
    dd = device_dictionary(dict_id)
    if dd is None:
        return None
    n = len(arr)
    validity = np.asarray(arr.is_valid()) if n else np.zeros(0, bool)
    idx = arr.indices
    codes = (np.asarray(idx.fill_null(0)).astype(np.int64) if n
             else np.zeros(0, np.int64))
    if remap is not None and n:
        codes = remap[np.clip(codes, 0, len(remap) - 1)].astype(np.int64)
        validity = validity & (codes >= 0)
        codes = np.where(codes >= 0, codes, 0)
    k = dd.num_values
    code_dt = np.int16 if k < _INT16_MAX_K else np.int32
    data = np.zeros(cap, dtype=code_dt)
    data[:n] = codes.astype(code_dt)
    vpad = np.zeros(cap, dtype=np.bool_)
    vpad[:n] = validity
    from spark_rapids_tpu.columnar.batch import DeviceColumn

    col = DeviceColumn(field.dataType, data, vpad,
                       vrange=(0, max(k - 1, 0)), encoding=dd)
    # savings ledger: what the padded-matrix upload WOULD have moved
    # vs what the codes move (the dictionary itself is ledgered once
    # at its own upload)
    hd = _host_dict(dict_id)
    if hd is not None:
        from spark_rapids_tpu.obs import telemetry

        plain = cap * (hd.matrix.shape[1] + 4 + 1)
        actual = data.nbytes + vpad.nbytes
        telemetry.record_encoded("scan.encode", actual, plain)
    return col


# --------------------------------------------------------------- decode

def decode_column(col):
    """Encoded column -> standard padded-matrix string column via an
    HBM-local dictionary gather. Trace-safe; identity for plain
    columns. This is the ONE in-device decode point — operators that
    cannot run on codes route through it."""
    dd = getattr(col, "encoding", None)
    if dd is None:
        return col
    k = dd.data.shape[0]
    codes = jnp.clip(col.data.astype(jnp.int32), 0, max(k - 1, 0))
    data = jnp.take(dd.data, codes, axis=0)
    lengths = jnp.take(dd.lengths, codes)
    # keep the zero-padding / zero-dead-rows invariants of the plain
    # string layout
    data = jnp.where(col.validity[:, None], data, 0)
    lengths = jnp.where(col.validity, lengths, 0)
    return col.replace(data=data, lengths=lengths, vrange=None,
                       encoding=None)


def align_encodings(cols):
    """Pre-concat normalization: keep the encoded representation only
    when EVERY piece is encoded with the SAME dictionary; any identity
    mismatch decodes all pieces (code spaces are not comparable across
    dictionaries)."""
    encs = [getattr(c, "encoding", None) for c in cols]
    if all(e is None for e in encs):
        return list(cols)
    if all(e is not None for e in encs) and \
            len({e.dict_id for e in encs}) == 1:
        return list(cols)
    return [decode_column(c) for c in cols]


def encoding_key(obj) -> tuple:
    """Per-column dictionary identities of a ColumnBatch (or a
    BuildTable wrapping one) — the fused engine folds this into its
    program keys so persistent/AOT artifacts never serve a program
    whose baked host probes belong to a different dictionary."""
    cols = getattr(obj, "columns", None)
    if cols is None:
        b = getattr(obj, "batch", None)
        cols = getattr(b, "columns", None)
    if cols is None:
        return ()
    return tuple(
        e.dict_id if (e := getattr(c, "encoding", None)) is not None
        else None
        for c in cols)


# ------------------------------------------- expression-level lowerings

def raw_column(expr, ctx):
    """The UNDECODED batch column behind a (possibly Alias-wrapped)
    BoundReference, or None when the expression is anything else."""
    from spark_rapids_tpu.expr.core import Alias, BoundReference

    if isinstance(expr, Alias):
        expr = expr.children[0]
    if isinstance(expr, BoundReference):
        return ctx.batch.columns[expr.ordinal]
    return None


def eval_preserving(expr, ctx):
    """Evaluate an expression, passing encoded columns through
    UNdecoded when the expression is a bare (aliased) column reference
    — the projection/grouping fast path that keeps codes flowing to
    the operators that can use them."""
    col = raw_column(expr, ctx)
    if col is not None and getattr(col, "encoding", None) is not None:
        return col
    return expr.eval(ctx)


def encoded_equality(left, right, ctx):
    """EqualTo fast path: `<encoded column> = <string literal>` (either
    side) compares CODES against one host-probed code — no decode, no
    byte-matrix comparison. Returns the boolean result column, or None
    when the shape doesn't apply."""
    from spark_rapids_tpu.expr.core import Literal
    from spark_rapids_tpu.sqltypes.datatypes import boolean

    ref, lit = left, right
    if isinstance(ref, Literal):
        ref, lit = right, left
    if not isinstance(lit, Literal) or not isinstance(lit.dtype,
                                                      StringType):
        return None
    col = raw_column(ref, ctx)
    if col is None:
        return None
    dd = getattr(col, "encoding", None)
    if dd is None:
        return None
    from spark_rapids_tpu.columnar.batch import DeviceColumn

    cap = col.capacity
    if lit.value is None:
        # `x = NULL` is null for every row
        return DeviceColumn(boolean, jnp.zeros((cap,), bool),
                            jnp.zeros((cap,), bool))
    code = probe_code(dd.dict_id, lit.value)
    if code is None:
        eq = jnp.zeros((cap,), bool)
    else:
        eq = col.data.astype(jnp.int32) == jnp.int32(code)
    return DeviceColumn(boolean, eq, col.validity)


class CodesOf(Expression):
    """Join-key lowering over an encoded column: evaluates to the
    column's integer CODES re-encoded into `dict_id`'s code space.
    Identity match is a free cast; a mismatched dictionary gathers
    through a host remap table (absent values -> -1, which matches no
    canonical code). Only valid over a BoundReference whose column is
    encoded — the caller (`_encoded_key_rewrite`) checks that before
    rewriting."""

    def __init__(self, child, dict_id: str):
        super().__init__([child])
        self.dict_id = dict_id

    @property
    def dtype(self):
        return _int_type

    @property
    def nullable(self):
        return self.children[0].nullable

    def key(self):
        return ("codesof", self.children[0].key(), self.dict_id)

    def eval(self, ctx):
        from spark_rapids_tpu.columnar.batch import DeviceColumn

        col = raw_column(self.children[0], ctx)
        dd = None if col is None else getattr(col, "encoding", None)
        if dd is None:
            raise TypeError(
                "CodesOf over a non-encoded column — the encoded join "
                "rewrite must only fire when both key columns carry "
                "dictionaries")
        codes = col.data.astype(jnp.int32)
        if dd.dict_id != self.dict_id:
            table = remap_table(dd.dict_id, self.dict_id)
            if table is None:
                raise TypeError(
                    f"no remap from dictionary {dd.dict_id} to "
                    f"{self.dict_id} (host dictionary evicted)")
            codes = jnp.take(jnp.asarray(table),
                             jnp.clip(codes, 0, table.shape[0] - 1))
        return DeviceColumn(_int_type, codes, col.validity)


def invalidate_device_cache() -> int:
    """Device-loss recovery hook (runtime/device_monitor.py): every
    cached DeviceDictionary was uploaded to the backend recovery just
    tore down — drop the device cache and release its catalog
    reservations. HOST dictionaries survive: the next
    `device_dictionary(dict_id)` call re-uploads the same content into
    the fresh backend (encoded columns re-intern lazily, like the warm
    executables). Returns how many device entries were dropped."""
    from spark_rapids_tpu.runtime.memory import _catalog

    with _lock:
        dev = list(_device_dicts.values())
        _device_dicts.clear()
    if _catalog is not None:
        for _, nbytes in dev:
            _catalog.release(nbytes, query_id=0)
    return len(dev)


def clear_for_tests() -> None:
    """Drop every interned dictionary (host + device) and release the
    device cache's catalog reservations — test isolation only."""
    from spark_rapids_tpu.runtime.memory import get_catalog

    with _lock:
        dev = list(_device_dicts.values())
        _device_dicts.clear()
        _host_dicts.clear()
    catalog = get_catalog()
    for _, nbytes in dev:
        catalog.release(nbytes, query_id=0)
