"""Dist packaging — the parallel-worlds jar analog.

The reference's dist module packs one artifact containing a common
class tree plus per-Spark-version "world" directories that ShimLoader
mounts at runtime (dist/build/package-parallel-worlds.py; layout doc
ShimLoader.scala:43-56). The Python equivalent builds a self-contained
dist directory:

    dist/spark_rapids_tpu-<version>/
        spark_rapids_tpu/...          # common tree (includes shims/)
        native/libsparktpu.so         # prebuilt native runtime
        MANIFEST.json                 # versions, shim worlds, file count

Run: python -m spark_rapids_tpu.tools.package_dist [out_dir]
"""

from __future__ import annotations

import json
import os
import shutil
import sys


def build_dist(out_dir: str = "dist") -> str:
    import spark_rapids_tpu
    from spark_rapids_tpu import shims

    repo = os.path.dirname(os.path.dirname(
        os.path.abspath(spark_rapids_tpu.__file__)))
    version = spark_rapids_tpu.__version__
    target = os.path.join(out_dir, f"spark_rapids_tpu-{version}")
    if os.path.exists(target):
        shutil.rmtree(target)
    os.makedirs(target, exist_ok=True)

    # common tree (shims ride inside as the parallel worlds)
    pkg_src = os.path.dirname(os.path.abspath(spark_rapids_tpu.__file__))
    shutil.copytree(
        pkg_src, os.path.join(target, "spark_rapids_tpu"),
        ignore=shutil.ignore_patterns("__pycache__", "*.pyc", "build"))
    # top-level worker module (forkserver pandas-UDF workers import it
    # WITHOUT importing the jax-initializing package)
    worker = os.path.join(repo, "srtpu_pandas_worker.py")
    if os.path.exists(worker):
        shutil.copy2(worker, target)

    # native runtime: prebuild so consumers need no toolchain
    native_src = os.path.join(repo, "native", "sparktpu_runtime.cpp")
    native_out = os.path.join(target, "native")
    os.makedirs(native_out, exist_ok=True)
    so = os.path.join(native_out, "libsparktpu.so")
    built = False
    if os.path.exists(native_src):
        from spark_rapids_tpu.native import compile_runtime

        # portable flags for a distributable artifact
        if compile_runtime(native_src, so, timeout=180,
                           native_arch=False):
            built = True
            # also drop it where the package loader probes first
            shutil.copy2(so, os.path.join(
                target, "spark_rapids_tpu", "native", "libsparktpu.so"))

    import importlib

    worlds = {}
    for name in shims._PROVIDERS:
        mod = importlib.import_module(name)
        worlds[name.rsplit(".", 1)[1]] = list(mod.VERSIONS)

    n_files = sum(len(fs) for _, _, fs in os.walk(target))
    manifest = {
        "version": version,
        "shim_worlds": worlds,
        "native_prebuilt": built,
        "files": n_files,
    }
    with open(os.path.join(target, "MANIFEST.json"), "w") as f:
        json.dump(manifest, f, indent=2)
    return target


def main() -> int:
    out = sys.argv[1] if len(sys.argv) > 1 else "dist"
    target = build_dist(out)
    with open(os.path.join(target, "MANIFEST.json")) as f:
        print(f.read())
    print("dist:", target)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
