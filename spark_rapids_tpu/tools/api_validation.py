"""API validation — the api_validation/ApiValidation.scala analog.

The reference audits constructor-signature drift between CPU execs and
their Gpu counterparts across every shim. Here two drift surfaces
matter:
1. shim worlds: every provider in spark_rapids_tpu.shims must export
   the identical API (names + call signatures), or a jax upgrade would
   silently change engine behavior per environment;
2. device/CPU operator pairs: every Tpu*Exec with a Cpu* sibling must
   agree on the leading constructor parameters the planner passes.

Run: python -m spark_rapids_tpu.tools.api_validation  (exit 1 on drift)
"""

from __future__ import annotations

import importlib
import inspect
from typing import List


def validate_shims() -> List[str]:
    from spark_rapids_tpu import shims

    problems = []
    mods = [importlib.import_module(n) for n in shims._PROVIDERS]
    for mod in mods:
        for name in shims.SHIM_API:
            if not hasattr(mod, name):
                problems.append(f"{mod.__name__} missing {name}")
    # signatures must agree across worlds
    for name in shims.SHIM_API:
        sigs = {}
        for mod in mods:
            obj = getattr(mod, name, None)
            if callable(obj):
                sigs[mod.__name__] = str(inspect.signature(obj))
        if len(set(sigs.values())) > 1:
            problems.append(f"shim API {name} signature drift: {sigs}")
    return problems


def validate_operator_pairs() -> List[str]:
    """Tpu*Exec vs Cpu*Exec constructor-prefix agreement (CpuSampleExec
    legitimately adds with_replacement; extra trailing params are
    allowed, renamed/reordered shared ones are not)."""
    from spark_rapids_tpu.exec import operators as ops

    problems = []
    names = dir(ops)
    for n in names:
        if not n.startswith("Tpu") or not n.endswith("Exec"):
            continue
        sibling = "Cpu" + n[3:]
        if sibling not in names:
            continue
        tsig = list(inspect.signature(
            getattr(ops, n).__init__).parameters)[1:]
        csig = list(inspect.signature(
            getattr(ops, sibling).__init__).parameters)[1:]
        shared = [p for p in tsig if p in csig]
        t_order = [p for p in tsig if p in shared]
        c_order = [p for p in csig if p in shared]
        if t_order != c_order:
            problems.append(
                f"{n}/{sibling}: shared ctor params ordered "
                f"{t_order} vs {c_order}")
        if not shared:
            problems.append(f"{n}/{sibling}: no shared ctor params")
    return problems


def main() -> int:
    problems = validate_shims() + validate_operator_pairs()
    for p in problems:
        print("DRIFT:", p)
    if not problems:
        print("api validation: OK")
    return 1 if problems else 0


if __name__ == "__main__":
    raise SystemExit(main())
