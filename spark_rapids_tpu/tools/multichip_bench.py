"""Multichip scaling bench: REAL q5 throughput at 1/2/4/8 shards.

The measurement ROADMAP item 1 asks for: the q5 join+agg shape executed
at increasing shard counts with the mesh SPMD engine (hash exchanges
compiled to on-device all-to-all over ICI, encoded codes on the wire),
against the incumbent single-chip engine at its DEFAULT configuration
(fused stage compiler, host-serialized MULTITHREADED shuffle).
Scaling is reported as ``throughput(mesh@n) / throughput(single@1)``:
the speedup a query sees when its execution spreads over n chips and
its shuffles stop leaving the device fabric.

On a machine without n real TPU chips the mesh is virtual (XLA host
devices timesharing the host cores): program shape, collective
semantics, and byte accounting are identical, but the n per-chip
programs run serially, so wall-clock measures their SUM where real
chips run them concurrently. Each mesh row therefore reports both the
serialized wall-clock (``median_s``) and the per-chip critical-path
estimate ``chip_est_s = median_s / n`` (q5's hash exchange balances
shards to within the slot-skew bound, so the per-chip max ~= the
mean); ``scaling`` uses the estimate on a virtual mesh and raw
wall-clock when the chips are real. ``virtual_mesh`` in the block says
which one you are reading.

Runnable in-process (``run_scaling``) when the interpreter already has
enough devices, or as ``python -m spark_rapids_tpu.tools.multichip_bench``
which prints one JSON line (bench.py spawns that in a virtual-mesh
subprocess).
"""

from __future__ import annotations

import json
import os
import statistics
import time
from typing import Dict, Sequence

ROWS = int(os.environ.get("SRTPU_MULTICHIP_ROWS", 2_000_000))
FILES = 8
STORES = 2000
REGIONS = 12
REPEATS = 3
DATA_DIR = f"/tmp/srtpu_multichip_{ROWS}"
DIM_DIR = f"/tmp/srtpu_multichip_{ROWS}_dim"


def ensure_data() -> int:
    """q5-shaped dataset: FILES fact parquet parts + a string-region
    dim (dictionary-encoded pages so the encoded path engages and the
    mesh ingestion must reconcile per-shard dictionaries). Returns
    fact arrow bytes."""
    import numpy as np
    import pyarrow as pa
    import pyarrow.parquet as pq

    marker = os.path.join(DATA_DIR, "_DONE")
    if os.path.exists(marker):
        return int(open(marker).read())
    os.makedirs(DATA_DIR, exist_ok=True)
    os.makedirs(DIM_DIR, exist_ok=True)
    rng = np.random.default_rng(0)
    per = ROWS // FILES
    total = 0
    for i in range(FILES):
        t = pa.table({
            "store": pa.array(rng.integers(0, STORES, per),
                              type=pa.int64()),
            "amount": pa.array(rng.random(per) * 100.0,
                               type=pa.float64()),
            "qty": pa.array(rng.integers(1, 100, per), type=pa.int64()),
        })
        total += t.nbytes
        pq.write_table(t, os.path.join(DATA_DIR, f"part-{i}.parquet"),
                       compression="NONE", use_dictionary=False,
                       row_group_size=per)
    dim = pa.table({
        "store": pa.array(np.arange(STORES), type=pa.int64()),
        "region": pa.array(
            [f"region_{i % REGIONS:02d}" for i in range(STORES)],
            type=pa.large_string()),
    })
    pq.write_table(dim, os.path.join(DIM_DIR, "dim.parquet"),
                   use_dictionary=["region"])
    with open(marker, "w") as f:
        f.write(str(total))
    return total


def _q5(spark):
    from spark_rapids_tpu.api import functions as F

    fact = spark.read.parquet(DATA_DIR)
    dim = spark.read.parquet(DIM_DIR)
    return (fact.filter(F.col("amount") > 10.0)
            .join(dim, on="store", how="inner")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("sales")))


def _session(extra: Dict) -> "object":
    from spark_rapids_tpu.api.session import TpuSparkSession

    conf = {
        "spark.sql.shuffle.partitions": 8,
        # shuffled join on both rows: the exchange IS the measurement
        "spark.sql.autoBroadcastJoinThreshold": -1,
    }
    conf.update(extra)
    return TpuSparkSession(conf)


def _timed_run(spark, repeats: int = REPEATS):
    df = _q5(spark)
    out = df.collect_arrow()  # cold: compiles + caches
    times = []
    for _ in range(repeats):
        t0 = time.perf_counter()
        out = df.collect_arrow()
        times.append(time.perf_counter() - t0)
    rec = spark.last_execution or {}
    return out, statistics.median(times), rec


def run_scaling(shards: Sequence[int] = (1, 2, 4, 8),
                repeats: int = REPEATS) -> Dict:
    """The MULTICHIP block: q5 throughput per shard count + the ledger's
    ici-vs-host byte split for the mesh execution."""
    import jax

    from spark_rapids_tpu.obs import telemetry

    input_bytes = ensure_data()
    need = max(shards)
    have = len(jax.devices())
    if have < need:
        raise RuntimeError(
            f"run_scaling needs {need} devices, have {have} "
            "(spawn under a virtual mesh: "
            "XLA_FLAGS=--xla_force_host_platform_device_count=8)")

    virtual = jax.devices()[0].platform == "cpu"
    rows = {}
    baseline_thr = None
    oracle = None
    # shards=1: the incumbent single-chip engine at its defaults
    # (fused stage compiler on, host-serialized MULTITHREADED shuffle)
    spark = _session({})
    try:
        out, med, rec = _timed_run(spark, repeats)
        oracle = {r: (round(v, 2), s) for r, v, s in zip(
            out.column("region").to_pylist(),
            out.column("rev").to_pylist(),
            out.column("sales").to_pylist())}
        baseline_thr = input_bytes / med / 1e9
        rows[1] = {
            "engine": rec.get("engine"),
            "median_s": round(med, 3),
            "gbps": round(baseline_thr, 3),
            "scaling": 1.0,
        }
    finally:
        spark.stop()

    mesh_ledgers = {}
    for n in shards:
        if n == 1:
            continue
        spark = _session({"spark.rapids.tpu.mesh": n})
        try:
            out, med, rec = _timed_run(spark, repeats)
            got = {r: (round(v, 2), s) for r, v, s in zip(
                out.column("region").to_pylist(),
                out.column("rev").to_pylist(),
                out.column("sales").to_pylist())}
            assert set(got) == set(oracle), (sorted(got), sorted(oracle))
            for k in oracle:
                assert got[k][1] == oracle[k][1], (k, got[k], oracle[k])
                assert abs(got[k][0] - oracle[k][0]) <= max(
                    1e-6 * abs(oracle[k][0]), 0.05), (k, got[k],
                                                      oracle[k])
            # on a virtual mesh one host core executes the n per-chip
            # programs serially: the chip critical path is med / n
            chip_est = med / n if virtual else med
            thr = input_bytes / chip_est / 1e9
            tel = (rec.get("telemetry") or {})
            moved = tel.get("bytesMoved") or {}
            rows[n] = {
                "engine": rec.get("engine"),
                "median_s": round(med, 3),
                "chip_est_s": round(chip_est, 3),
                "gbps": round(thr, 3),
                "scaling": round(thr / baseline_thr, 3),
                "iciBytes": tel.get("iciBytes"),
                "hostBytesAvoided": tel.get("hostBytesAvoided"),
                "shuffleHostBytes": moved.get("shuffle", 0),
            }
            mesh_ledgers[n] = moved
        finally:
            spark.stop()

    top = max(n for n in shards if n in rows)
    dev = jax.devices()[0]
    moved_top = mesh_ledgers.get(top, {})
    return {
        "metric": "q5 scan+join+agg throughput by shard count "
                  "(mesh SPMD over ICI vs default single-chip engine)",
        "rows": ROWS,
        "input_mib": input_bytes >> 20,
        "device_kind": str(getattr(dev, "device_kind", dev.platform)),
        "virtual_mesh": virtual,
        "baseline": "single-chip engine, default conf "
                    "(fused, MULTITHREADED host shuffle)",
        "shards": {str(k): v for k, v in sorted(rows.items())},
        "scaling_at_%d" % top: rows[top]["scaling"],
        "scaling_efficiency_at_%d" % top: round(
            rows[top]["scaling"] / top, 3),
        # the proof the exchange left the host: mesh execution moved
        # ICI bytes and ZERO shuffle-direction (host) bytes
        "ici_vs_h2d": {
            "ici": moved_top.get("ici", 0),
            "h2d": moved_top.get("h2d", 0),
            "shuffle_host": moved_top.get("shuffle", 0),
        },
        "process_ici": telemetry.ledger.registry_view().get("ici"),
    }


def _agg_only(spark):
    from spark_rapids_tpu.api import functions as F

    return (spark.read.parquet(DATA_DIR)
            .groupBy("store")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("sales")))


def run_hosts(repeats: int = REPEATS) -> Dict:
    """The multi-host axis (PR 17): the SAME 8 chips flat (1x8 — every
    exchange on ICI) vs split into two simulated host failure domains
    (2x4 — hash exchanges keep their heavy stage on ICI, only the
    cross-host stage and reduced partial-agg buffers cross DCN). On one
    machine both fabrics are the same host backplane, so wall-clock is
    flat by construction; the measurement is the LEDGER split the
    DCN-aware planner produces: `dcn_vs_ici` for the q5 exchange-bearing
    plan (must stay < 1), and `dcn_reduction_factor` (ici/dcn) for an
    agg-only shape — the factor by which the reduce-then-DCN placement
    keeps traffic on the fast fabric rather than the cross-host links."""
    ensure_data()

    def ledger(spark, q):
        out = q(spark).collect_arrow()
        rec = spark.last_execution or {}
        tel = rec.get("telemetry") or {}
        moved = tel.get("bytesMoved") or {}
        return out, rec.get("engine"), {
            "iciBytes": moved.get("ici", 0),
            "dcnBytes": moved.get("dcn", 0),
        }

    spark = _session({"spark.rapids.tpu.mesh": 8})
    try:
        out_flat, eng_flat, flat = ledger(spark, _q5)
    finally:
        spark.stop()

    spark = _session({"spark.rapids.tpu.mesh": 8,
                      "spark.rapids.tpu.multihost.simulatedHosts": 2})
    try:
        out_2x4, eng_2x4, q5_2x4 = ledger(spark, _q5)
        _, _, agg_2x4 = ledger(spark, _agg_only)
    finally:
        spark.stop()

    assert eng_flat == "mesh" and eng_2x4 == "mesh", (eng_flat, eng_2x4)
    flat_rev = {r: round(v, 2) for r, v in zip(
        out_flat.column("region").to_pylist(),
        out_flat.column("rev").to_pylist())}
    rev_2x4 = {r: round(v, 2) for r, v in zip(
        out_2x4.column("region").to_pylist(),
        out_2x4.column("rev").to_pylist())}
    assert set(flat_rev) == set(rev_2x4), (flat_rev, rev_2x4)

    dcn, ici = q5_2x4["dcnBytes"], q5_2x4["iciBytes"]
    adcn, aici = agg_2x4["dcnBytes"], agg_2x4["iciBytes"]
    return {
        "metric": "q5 byte placement, 1x8 flat vs 2x4 host domains "
                  "(hash exchanges on ICI, reduced traffic on DCN)",
        "q5_1x8": flat,
        "q5_2x4": {**q5_2x4,
                   "dcn_vs_ici": round(dcn / ici, 3) if ici else None},
        "agg_2x4": agg_2x4,
        "dcn_reduction_factor": round(aici / adcn, 3) if adcn else None,
    }


def main() -> None:
    block = run_scaling()
    block["hosts"] = run_hosts()
    print(json.dumps(block))


if __name__ == "__main__":
    main()
