"""srtpu-lint — the repo's AST rule engine for machine-checked
invariants (see docs/static-analysis.md and ci/static_check.sh).

Five PRs of review-memory invariants (every conf registered and
documented, every blocking wait cancel-interruptible, every
byte-crossing site ledgered, every emitted event schema-registered,
no bare excepts) become static analysis here: `python -m
spark_rapids_tpu.tools.lint` walks `spark_rapids_tpu/` and exits
non-zero on any finding. Suppress a single line with an inline
`# srtpu-lint: disable=<rule-id>` pragma.
"""

from spark_rapids_tpu.tools.lint.engine import (  # noqa: F401
    Finding,
    LintEngine,
    RepoContext,
)
from spark_rapids_tpu.tools.lint.rules import all_rules  # noqa: F401
