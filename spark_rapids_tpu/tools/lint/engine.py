"""Rule-engine core: file walking, AST parsing, pragma suppression,
and the shared repo context (declared confs, documented confs,
registered event types) rules check against.

Design mirrors small linters (flake8 plugins, the reference repo's
scala-style checks in ci/): a Rule sees one parsed file at a time plus
a RepoContext of cross-file facts; repo-scoped rules run once over the
context. Everything is stdlib `ast` — no third-party dependency, so
the CI gate runs anywhere the engine does.
"""

from __future__ import annotations

import ast
import importlib.util
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Set

PRAGMA_RE = re.compile(r"#\s*srtpu-lint:\s*disable=([A-Za-z0-9_,\- ]+)")


@dataclass
class Finding:
    rule: str
    path: str          # repo-relative
    line: int
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: [{self.rule}] {self.message}"


@dataclass
class FileContext:
    """One parsed source file plus its suppression table."""

    path: str                      # absolute
    rel: str                       # repo-relative, '/'-separated
    source: str
    tree: ast.AST
    suppressions: Dict[int, Set[str]] = field(default_factory=dict)
    _func_spans: Optional[List[tuple]] = None

    @classmethod
    def parse(cls, path: str, rel: str) -> "FileContext":
        with open(path, encoding="utf-8") as f:
            source = f.read()
        ctx = cls(path=path, rel=rel, source=source,
                  tree=ast.parse(source, filename=path))
        for i, line in enumerate(source.splitlines(), 1):
            m = PRAGMA_RE.search(line)
            if m:
                ctx.suppressions[i] = {
                    r.strip() for r in m.group(1).split(",") if r.strip()}
        return ctx

    def suppressed(self, line: int, rule: str) -> bool:
        rules = self.suppressions.get(line)
        return bool(rules) and (rule in rules or "*" in rules)

    # --- enclosing-function helpers (several rules scope their
    # --- exemptions to "the function this call lives in") ---

    def _spans(self) -> List[tuple]:
        if self._func_spans is None:
            spans = []
            for node in ast.walk(self.tree):
                if isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                    spans.append((node.lineno,
                                  node.end_lineno or node.lineno,
                                  node))
            # innermost (shortest) span wins on lookup
            spans.sort(key=lambda s: (s[0], -(s[1])))
            self._func_spans = spans
        return self._func_spans

    def enclosing_function(self, line: int
                           ) -> Optional[ast.FunctionDef]:
        fns = self.enclosing_functions(line)
        return fns[0] if fns else None

    def enclosing_functions(self, line: int) -> List[ast.FunctionDef]:
        """Every function whose span contains `line`, innermost first
        — a closure nested in an instrumented function counts as
        instrumented."""
        hits = [(hi - lo, node) for lo, hi, node in self._spans()
                if lo <= line <= hi]
        hits.sort(key=lambda t: t[0])
        return [node for _span, node in hits]


class RepoContext:
    """Cross-file facts the rules need: the conf registry (imported
    from config/rapids_conf.py so dynamically-built keys resolve), the
    documented-key set (regexed out of docs/configs.md), and the obs
    event-type registry (statically parsed out of obs/events.py — it
    is a literal dict)."""

    KEY_RE = re.compile(
        r"spark\.rapids\.tpu\.[A-Za-z0-9][A-Za-z0-9.]*[A-Za-z0-9]")

    def __init__(self, root: str):
        self.root = root
        self.pkg = os.path.join(root, "spark_rapids_tpu")
        self.declared_confs: Set[str] = set()
        self.internal_confs: Set[str] = set()
        self.documented_confs: Set[str] = set()
        self.event_types: Set[str] = set()
        self._load_confs()
        self._load_docs()
        self._load_event_types()

    def _load_confs(self) -> None:
        """Import rapids_conf.py standalone (it is stdlib-only) so
        registry keys built through helpers/f-strings are exact — a
        static walk would miss every `_format_read_enable`-style
        constructor."""
        path = os.path.join(self.pkg, "config", "rapids_conf.py")
        spec = importlib.util.spec_from_file_location(
            "_srtpu_lint_rapids_conf", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        for key, entry in mod._REGISTRY.items():
            self.declared_confs.add(key)
            if getattr(entry, "internal", False):
                self.internal_confs.add(key)

    def _load_docs(self) -> None:
        path = os.path.join(self.root, "docs", "configs.md")
        if not os.path.exists(path):
            return
        with open(path, encoding="utf-8") as f:
            text = f.read()
        self.documented_confs = set(self.KEY_RE.findall(text))

    def _load_event_types(self) -> None:
        path = os.path.join(self.pkg, "obs", "events.py")
        tree = ast.parse(open(path, encoding="utf-8").read())
        for node in ast.walk(tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value:
                targets = [node.target]
            for t in targets:
                if isinstance(t, ast.Name) and t.id == "EVENT_TYPES" \
                        and isinstance(node.value, ast.Dict):
                    for k in node.value.keys:
                        if isinstance(k, ast.Constant) and \
                                isinstance(k.value, str):
                            self.event_types.add(k.value)

    def is_registered_or_family(self, key: str) -> bool:
        """True when `key` is a registered conf OR a strict prefix of
        one (doc prose references families like
        `spark.rapids.tpu.admission.queue` without naming a leaf)."""
        if key in self.declared_confs:
            return True
        prefix = key + "."
        return any(k.startswith(prefix) for k in self.declared_confs)

    def is_documented_or_family(self, key: str) -> bool:
        if key in self.documented_confs:
            return True
        prefix = key + "."
        return any(k.startswith(prefix) for k in self.documented_confs)


class Rule:
    """One invariant. `check` sees each file; `repo_check` runs once
    per lint run for cross-file invariants."""

    id: str = "rule"
    description: str = ""

    def check(self, ctx: FileContext, repo: RepoContext
              ) -> Iterable[Finding]:
        return ()

    def repo_check(self, repo: RepoContext) -> Iterable[Finding]:
        return ()


class LintEngine:
    SKIP_DIRS = {"__pycache__"}

    def __init__(self, root: str, rules: Optional[List[Rule]] = None):
        from spark_rapids_tpu.tools.lint.rules import all_rules

        self.root = os.path.abspath(root)
        self.rules = rules if rules is not None else all_rules()
        self.repo = RepoContext(self.root)
        self.parse_errors: List[Finding] = []

    def files(self) -> List[str]:
        out = []
        for dirpath, dirnames, filenames in os.walk(
                os.path.join(self.root, "spark_rapids_tpu")):
            dirnames[:] = [d for d in dirnames
                           if d not in self.SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
        return out

    def run(self, paths: Optional[List[str]] = None) -> List[Finding]:
        findings: List[Finding] = []
        for path in (paths if paths is not None else self.files()):
            rel = os.path.relpath(path, self.root).replace(os.sep, "/")
            try:
                ctx = FileContext.parse(path, rel)
            except SyntaxError as e:
                findings.append(Finding("parse-error", rel,
                                        e.lineno or 0, str(e.msg)))
                continue
            for rule in self.rules:
                for f in rule.check(ctx, self.repo):
                    if not ctx.suppressed(f.line, f.rule):
                        findings.append(f)
        for rule in self.rules:
            findings.extend(rule.repo_check(self.repo))
        findings.sort(key=lambda f: (f.path, f.line, f.rule))
        return findings


def repo_root() -> str:
    """The checkout root, derived from this file's location
    (spark_rapids_tpu/tools/lint/engine.py -> three levels up)."""
    here = os.path.dirname(os.path.abspath(__file__))
    return os.path.abspath(os.path.join(here, "..", "..", ".."))
