"""The invariant rules. Each encodes one discipline previous PRs
enforced only through reviewer memory; docs/static-analysis.md carries
the id, rationale, and suppression notes for every rule here.

Rule ids are stable (suppressions and commit messages reference them):

- conf-registered    every spark.rapids.tpu.* key read in source is
                     declared in config/rapids_conf.py
- conf-documented    every declared key appears in docs/configs.md
- raw-sleep          no time.sleep outside runtime/backoff.py and
                     runtime/cancellation.py (use sleep_interruptible)
- unyielding-wait    no indefinitely-blocking acquire/join/get in
                     modules that can hold semaphore permits unless a
                     cancellation yield point is in scope
- raw-transfer       device_put/device_get (and shuffle-path binary
                     file writes) only inside telemetry-instrumented
                     functions
- unknown-event      emitted event-type literals exist in
                     obs/events.py EVENT_TYPES
- bare-except        no `except:` without an exception class
"""

from __future__ import annotations

import ast
from typing import Iterable, List

from spark_rapids_tpu.tools.lint.engine import (
    Finding,
    FileContext,
    RepoContext,
    Rule,
)


def _call_name(node: ast.Call) -> str:
    """Dotted-ish name of the called object: 'time.sleep' for
    time.sleep(...), 'sleep' for sleep(...), '.get' for obj.get(...)
    where the value is not a plain Name."""
    f = node.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        if isinstance(f.value, ast.Name):
            return f"{f.value.id}.{f.attr}"
        return f".{f.attr}"
    return ""


def _function_contains(fn: ast.AST, attr_names: set,
                       name_substrings: set = frozenset()) -> bool:
    for node in ast.walk(fn):
        if isinstance(node, ast.Attribute) and node.attr in attr_names:
            return True
        if isinstance(node, ast.Name) and any(
                s in node.id.lower() for s in name_substrings):
            return True
    return False


class ConfRegisteredRule(Rule):
    id = "conf-registered"
    description = ("every spark.rapids.tpu.* key appearing in a "
                   "string literal is declared in "
                   "config/rapids_conf.py")
    #: the declaration site itself and generated-docs tooling are the
    #: registry, not readers of it
    EXEMPT = {"spark_rapids_tpu/config/rapids_conf.py"}

    def check(self, ctx: FileContext, repo: RepoContext
              ) -> Iterable[Finding]:
        if ctx.rel in self.EXEMPT:
            return
        seen = set()
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Constant)
                    and isinstance(node.value, str)):
                continue
            for m in repo.KEY_RE.finditer(node.value):
                key = m.group(0)
                # family references ("spark.rapids.tpu.admission.*",
                # "...sanitizer.{enabled,...}") resolve as prefixes
                if repo.is_registered_or_family(key):
                    continue
                mark = (node.lineno, key)
                if mark in seen:
                    continue
                seen.add(mark)
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    f"conf key '{key}' is not declared in "
                    f"config/rapids_conf.py (register it with conf() "
                    f"so it is typed, defaulted, and documented)")


class ConfDocumentedRule(Rule):
    id = "conf-documented"
    description = ("every declared, non-internal conf key appears in "
                   "docs/configs.md (regenerate with "
                   "python -m spark_rapids_tpu.tools.gendocs)")

    def repo_check(self, repo: RepoContext) -> Iterable[Finding]:
        for key in sorted(repo.declared_confs - repo.internal_confs):
            if not key.startswith("spark.rapids.tpu."):
                continue  # the invariant covers the tpu namespace
            if not repo.is_documented_or_family(key):
                yield Finding(
                    self.id, "docs/configs.md", 1,
                    f"declared conf key '{key}' is missing from "
                    f"docs/configs.md — regenerate the doc")


class RawSleepRule(Rule):
    id = "raw-sleep"
    description = ("time.sleep only inside runtime/backoff.py and "
                   "runtime/cancellation.py; everything else uses "
                   "cancellation.sleep_interruptible so a cancelled "
                   "query never rides out a delay")
    ALLOWED = {"spark_rapids_tpu/runtime/backoff.py",
               "spark_rapids_tpu/runtime/cancellation.py"}

    def check(self, ctx: FileContext, repo: RepoContext
              ) -> Iterable[Finding]:
        if ctx.rel in self.ALLOWED:
            return
        from_time_sleep = any(
            isinstance(n, ast.ImportFrom) and n.module == "time"
            and any(a.name == "sleep" for a in n.names)
            for n in ast.walk(ctx.tree))
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            if name == "time.sleep" or \
                    (name == "sleep" and from_time_sleep):
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "raw time.sleep blocks cancellation — use "
                    "runtime.cancellation.sleep_interruptible (falls "
                    "back to time.sleep without a token in scope)")


class UnyieldingWaitRule(Rule):
    id = "unyielding-wait"
    description = ("no indefinitely-blocking .acquire()/.join()/"
                   ".get() in modules that can hold semaphore permits "
                   "unless a cancellation yield point is in scope in "
                   "the enclosing function")
    #: modules whose code can run while the query holds device-
    #: semaphore permits — a blocking wait here is a deadlock
    #: ingredient (hold-and-wait)
    PERMIT_MODULES = {
        "spark_rapids_tpu/exec/base.py",
        "spark_rapids_tpu/exec/operators.py",
        "spark_rapids_tpu/exec/fused.py",
        "spark_rapids_tpu/exec/joins.py",
        "spark_rapids_tpu/exec/agg_pushdown.py",
        "spark_rapids_tpu/api/columnar_rdd.py",
        "spark_rapids_tpu/shuffle/manager.py",
        "spark_rapids_tpu/runtime/retry.py",
        "spark_rapids_tpu/runtime/scheduler.py",
        "spark_rapids_tpu/runtime/memory.py",
    }
    BLOCKING_ATTRS = {"acquire", "join", "get"}

    @staticmethod
    def _queue_like(node: ast.Call) -> bool:
        """`.get()` is only a blocking wait on queue-like receivers —
        module singleton getters (`sem.get()`, `host_alloc.get()`) and
        dict/ContextVar gets are not waits. Receiver names matching
        q/queue/future conventions count."""
        import re

        v = node.func.value
        name = ""
        if isinstance(v, ast.Name):
            name = v.id
        elif isinstance(v, ast.Attribute):
            name = v.attr
        return bool(re.search(r"(^|_)(q|queue|future)s?$|queue",
                              name, re.I))

    @classmethod
    def _is_blocking(cls, node: ast.Call, attr: str) -> bool:
        """Heuristic for 'waits indefinitely': a zero-positional-arg
        call with no timeout= kwarg and no blocking=False. dict.get /
        str.join style calls always pass positionals and drop out."""
        if node.args:
            return False
        for kw in node.keywords:
            if kw.arg == "timeout":
                return False
            if kw.arg in ("blocking", "block") and \
                    isinstance(kw.value, ast.Constant) and \
                    kw.value.value is False:
                return False
        if attr == "get":
            return cls._queue_like(node)
        return True

    def check(self, ctx: FileContext, repo: RepoContext
              ) -> Iterable[Finding]:
        if ctx.rel not in self.PERMIT_MODULES:
            return
        for node in ast.walk(ctx.tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in self.BLOCKING_ATTRS):
                continue
            if not self._is_blocking(node, node.func.attr):
                continue
            if any(_function_contains(
                    fn, {"check", "on_cancel", "check_current",
                         "sleep_interruptible"},
                    {"cancel", "token"})
                    for fn in ctx.enclosing_functions(node.lineno)):
                continue  # a yield point is in scope
            yield Finding(
                self.id, ctx.rel, node.lineno,
                f"indefinitely-blocking .{node.func.attr}() in a "
                f"permit-holding module with no cancellation yield "
                f"point in scope — pass a timeout, check a "
                f"CancelToken, or register an on_cancel wakeup")


class RawTransferRule(Rule):
    id = "raw-transfer"
    description = ("host<->device byte crossings (jax.device_put / "
                   "jax.device_get) and shuffle/spill binary file "
                   "writes happen only in telemetry-instrumented "
                   "functions (obs/telemetry.py record/ledgered_*), "
                   "so the data-movement ledger stays complete")
    #: the instrumentation layer itself
    EXEMPT = {"spark_rapids_tpu/obs/telemetry.py"}
    RECORDERS = {"record", "ledgered_get", "ledgered_put",
                 "record_forwarded", "_disk_io"}
    WRITE_MODULES_PREFIX = ("spark_rapids_tpu/shuffle/",)

    def check(self, ctx: FileContext, repo: RepoContext
              ) -> Iterable[Finding]:
        if ctx.rel in self.EXEMPT:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            name = _call_name(node)
            is_transfer = name.endswith("device_put") or \
                name.endswith("device_get")
            is_binary_write = (
                ctx.rel.startswith(self.WRITE_MODULES_PREFIX)
                and name == "open" and len(node.args) >= 2
                and isinstance(node.args[1], ast.Constant)
                and isinstance(node.args[1].value, str)
                and "b" in node.args[1].value
                and any(c in node.args[1].value for c in "wa"))
            if not (is_transfer or is_binary_write):
                continue
            if any(_function_contains(fn, self.RECORDERS)
                    for fn in ctx.enclosing_functions(node.lineno)):
                continue  # instrumented in this (or an enclosing) fn
            what = ("byte-crossing transfer" if is_transfer
                    else "shuffle-path binary file write")
            yield Finding(
                self.id, ctx.rel, node.lineno,
                f"unledgered {what} — route it through the "
                f"obs.telemetry wrappers (telemetry.record around the "
                f"crossing, or telemetry.ledgered_put/ledgered_get) "
                f"so per-query data-movement accounting stays exact")


class UnknownEventRule(Rule):
    id = "unknown-event"
    description = ("event-type literals passed to emit() exist in "
                   "obs/events.py EVENT_TYPES (the eventlog validator "
                   "rejects anything else)")
    EXEMPT = {"spark_rapids_tpu/obs/events.py"}

    def check(self, ctx: FileContext, repo: RepoContext
              ) -> Iterable[Finding]:
        if ctx.rel in self.EXEMPT:
            return
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            name = _call_name(node)
            if not (name == "emit" or name.endswith(".emit")):
                continue
            arg = node.args[0]
            if isinstance(arg, ast.Constant) and \
                    isinstance(arg.value, str) and \
                    arg.value not in repo.event_types:
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    f"event type '{arg.value}' is not registered in "
                    f"obs/events.py EVENT_TYPES — the eventlog "
                    f"validator would reject it; register the type "
                    f"with its payload summary")


class BareExceptRule(Rule):
    id = "bare-except"
    description = ("no `except:` — it swallows KeyboardInterrupt and "
                   "cancellation errors; catch Exception (or the "
                   "specific class) instead")

    def check(self, ctx: FileContext, repo: RepoContext
              ) -> Iterable[Finding]:
        for node in ast.walk(ctx.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield Finding(
                    self.id, ctx.rel, node.lineno,
                    "bare `except:` swallows BaseException (including "
                    "query cancellation) — name the exception class")


def all_rules() -> List[Rule]:
    return [ConfRegisteredRule(), ConfDocumentedRule(), RawSleepRule(),
            UnyieldingWaitRule(), RawTransferRule(), UnknownEventRule(),
            BareExceptRule()]
