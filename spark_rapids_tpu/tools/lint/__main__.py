"""CLI: `python -m spark_rapids_tpu.tools.lint [--root DIR] [--json]`.

Exit status 0 = clean tree, 1 = findings (what ci/static_check.sh
gates on), 2 = engine error.
"""

from __future__ import annotations

import argparse
import json
import sys

from spark_rapids_tpu.tools.lint.engine import LintEngine, repo_root
from spark_rapids_tpu.tools.lint.rules import all_rules


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="srtpu-lint")
    p.add_argument("--root", default=repo_root(),
                   help="checkout root (contains spark_rapids_tpu/ "
                        "and docs/)")
    p.add_argument("--json", action="store_true",
                   help="machine-readable findings")
    p.add_argument("--list-rules", action="store_true")
    args = p.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for r in rules:
            print(f"{r.id}: {r.description}")
        return 0

    engine = LintEngine(args.root, rules)
    findings = engine.run()
    if args.json:
        print(json.dumps({
            "ruleCount": len(rules),
            "findingCount": len(findings),
            "findings": [vars(f) for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f.render())
        print(f"srtpu-lint: {len(findings)} finding(s) across "
              f"{len(engine.files())} file(s), {len(rules)} rule(s)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
