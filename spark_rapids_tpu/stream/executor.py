"""Out-of-core streaming executor: bounded device windows with
double-buffered H2D prefetch.

Three pipelined stages with NO global barrier, so a table many times
larger than HBM runs at link speed:

1. PREFETCH (stream/prefetch.py): reader threads decode row-group
   ScanUnits into a bounded host staging queue (io.read backoff,
   stream.prefetch chaos re-enqueues the unit).
2. UPLOAD (one thread here): double-buffered async H2D — each staged
   table admits into the DeviceWindow (stream/window.py byte budget),
   uploads via the fused engine's `upload_narrowed` (ints narrowed to
   their value range, low-cardinality strings streamed as dictionary
   CODES), registers with the SpillCatalog, and hands the slot to
   compute through a depth-2 queue: one slot uploading while one
   computes.
3. COMPUTE (caller's thread): runs the streamable operator chain
   (filter/project/partial-or-complete agg/broadcast-join probe) over
   each window slot, retires the result to host, releases the slot.

Recovery: `device.fatal` mid-stream fences the device
(runtime/device_monitor.py) and cancels this query — the executor
unwinds CLEANLY (threads stopped, slots closed, permit released) and
re-raises DeviceLostError so the outermost collect's one-shot
resubmit (api/dataframe.py collect_arrow) re-runs the query after
warm recovery. Retired partitions are NOT lost: a plan-fingerprint
lineage cache keeps each retired host table (host memory survives
device loss), and the resubmitted run skips straight past them —
resume from the last retired partition, not from byte zero.

Telemetry: h2d and compute busy intervals feed the per-query
`overlapFraction` (obs/telemetry.py), with `windowPeakBytes` /
`partitionsStreamed` / `streamRecoveries` on the query summary and
`stream.{start,partition,window,end}` on the event bus.
"""

from __future__ import annotations

import queue
import threading
import time
from collections import OrderedDict
from typing import Dict, List, Optional

import pyarrow as pa

from spark_rapids_tpu.exec.base import PhysicalPlan, new_task_context
from spark_rapids_tpu.io import readers
from spark_rapids_tpu.stream import prefetch as _prefetch
from spark_rapids_tpu.stream.planner import StreamPlan, plan_stream
from spark_rapids_tpu.stream.window import DeviceWindow, window_budget

# ------------------------------------------------- mid-stream lineage
#
# fingerprint -> {"units": [ScanUnit...], "retired": {unit_key: table}}
# An entry is POPPED at execution start and re-stored ONLY when the
# run unwinds on DeviceLostError — the resubmitted run (same logical
# plan, same fingerprint) resumes from the retired set, and any other
# outcome (success, demotion, cancel) drops the entry so a later
# identical query always streams fresh data. Bounded: an orphaned
# entry (loss with resubmit disabled) ages out.

_LINEAGE_KEEP = 4
_lineage_lock = threading.Lock()
_lineage: "OrderedDict[tuple, dict]" = OrderedDict()


def _lineage_pop(key):
    with _lineage_lock:
        return _lineage.pop(key, None)


def _lineage_store(key, entry) -> None:
    with _lineage_lock:
        _lineage[key] = entry
        _lineage.move_to_end(key)
        while len(_lineage) > _LINEAGE_KEEP:
            _lineage.popitem(last=False)


def _unit_key(unit: readers.ScanUnit) -> tuple:
    return (unit.path, unit.row_groups)


def _arrow_schema(schema) -> pa.Schema:
    from spark_rapids_tpu.sqltypes.datatypes import to_arrow_type

    return pa.schema([
        pa.field(f.name, to_arrow_type(f.dataType), f.nullable)
        for f in schema.fields])


def _empty_table(schema) -> pa.Table:
    return _arrow_schema(schema).empty_table()


class StreamedSourceExec(PhysicalPlan):
    """Source node substituting retired host partitions for the
    streamed chain top: once the out-of-core prefix has retired, the
    ordinary engines run the plan REMAINDER (shuffles, final aggs,
    sorts) over these partitions like any other scan output."""

    is_tpu = True

    def __init__(self, tables: List[pa.Table], schema, conf):
        super().__init__([], schema, conf)
        self._tables = tables

    @property
    def num_partitions(self) -> int:
        return max(1, len(self._tables))

    def execute_partition(self, pid, ctx):
        from spark_rapids_tpu.columnar.arrow_bridge import arrow_to_device
        from spark_rapids_tpu.exec.operators import _acquire

        if pid >= len(self._tables):
            return
        t = self._tables[pid]
        if t.num_rows == 0:
            return
        _acquire(ctx)
        yield arrow_to_device(t)


class StreamExecutor:
    """Drive one query through the streaming pipeline."""

    def __init__(self, conf):
        self.conf = conf

    # ------------------------------------------------------ planning

    def execute(self, phys) -> pa.Table:
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import admission, degrade
        from spark_rapids_tpu.runtime.errors import DeviceLostError

        sp = plan_stream(phys, self.conf)  # StreamCompileError rides up
        scan = sp.scan
        handle = admission.current_handle()
        priority = handle.priority if handle is not None else 0
        budget = window_budget(self.conf, priority)
        cols = scan.pushed_columns
        read_dict = scan._dict_columns(cols)
        fkey = ("stream",) + degrade.plan_fingerprint(phys)

        lineage = _lineage_pop(fkey)
        if lineage is None:
            # unit size ~ a quarter window: 2 staged + 1 uploading +
            # 1 computing keeps the window full without one unit
            # monopolizing it. The packing target is in parquet
            # METADATA bytes (page-encoded), which undercount the
            # decoded+padded arrow size by ~DECODE_EXPANSION.
            from spark_rapids_tpu.stream.planner import DECODE_EXPANSION

            units = readers.split_scan_units(
                [f for task in scan._tasks for f in task],
                unit_bytes=max(64 << 10,
                               budget // (4 * DECODE_EXPANSION)),
                filters=scan.pushed_filters,
                read_dictionary=read_dict)
            retired: Dict[tuple, pa.Table] = {}
        else:
            # resume: the SAME unit boundaries (a fresh split under
            # post-recovery free-HBM could shift them, orphaning the
            # retired set) and the retired host tables survive
            units = lineage["units"]
            retired = lineage["retired"]

        todo = [u for u in units if _unit_key(u) not in retired]
        resumed = len(units) - len(todo)
        obs_events.emit("stream.start", partitions=len(units),
                        windowBytes=budget,
                        prefetchThreads=self.conf.get(
                            rc.STREAM_PREFETCH_THREADS))
        if resumed:
            obs_events.emit("stream.window", action="recover",
                            bytes=0, inUse=resumed)
        if self.conf.get(rc.STREAM_MESH_ENABLED):
            from spark_rapids_tpu.stream.mesh import plan_mesh_slots

            plan_mesh_slots(units)

        try:
            ordered = self._stream(sp, units, todo, retired, budget,
                                   cols, read_dict, resumed)
        except DeviceLostError:
            # host-resident retirements survive the loss; the one-shot
            # resubmit (collect_arrow) resumes from them
            _lineage_store(fkey, {"units": units, "retired": retired})
            raise
        # remainder (shuffles, final aggs, ...) runs AFTER the stream's
        # device permit released — base.collect drives its own tasks
        if sp.parent is None:
            good = [t for t in ordered if t.num_rows > 0]
            if not good:
                return _empty_table(sp.chain_top.schema)
            return pa.concat_tables(good, promote_options="none")
        return self._run_remainder(sp, ordered, phys)

    # ------------------------------------------------------ pipeline

    def _stream(self, sp: StreamPlan, units, todo, retired, budget,
                cols, read_dict, resumed) -> List[pa.Table]:
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime import cancellation
        from spark_rapids_tpu.runtime import semaphore as sem
        from spark_rapids_tpu.runtime.memory import get_catalog

        conf = self.conf
        qid = obs_events.current_query_id()
        token = cancellation.current()
        catalog = get_catalog()
        window = DeviceWindow(budget)
        ctx = new_task_context(conf)
        chain_top = sp.chain_top

        prefetcher = _prefetch.Prefetcher(
            todo, cols, scan_batch_rows(sp.scan),
            num_threads=conf.get(rc.STREAM_PREFETCH_THREADS),
            read_dictionary=read_dict, cancel_token=token)
        # depth 2 = the DOUBLE buffer: one slot computing, one uploaded
        # and on deck, prefetch decode running ahead of both
        compute_q: "queue.Queue" = queue.Queue(maxsize=2)
        upload_done = object()
        h2d_spans: List[tuple] = []
        compute_spans: List[tuple] = []

        def cq_put(item) -> bool:
            # never wedge on a consumer that already unwound: the
            # depth-2 queue is only drained while the main loop lives
            while not prefetcher.abandoned.is_set():
                try:
                    compute_q.put(item, timeout=0.2)
                    return True
                except queue.Full:
                    continue
            return False

        def uploader():
            from spark_rapids_tpu.exec.fused import upload_narrowed

            with cancellation.scope(token), obs_events.task_scope(
                    stage=0, task=ctx.task_id, attempt=0, query_id=qid):
                try:
                    while not prefetcher.abandoned.is_set():
                        try:
                            item = prefetcher.staging.get(timeout=0.2)
                        except queue.Empty:
                            continue
                        if item is _prefetch.PREFETCH_DONE:
                            cq_put(upload_done)
                            return
                        if isinstance(item, BaseException):
                            cq_put(item)
                            return
                        idx, unit, table = item
                        if table.num_rows == 0:
                            cq_put((idx, unit, None, 0))
                            continue
                        admitted = window.admit(table.nbytes)
                        obs_events.emit("stream.window", action="admit",
                                        bytes=admitted,
                                        inUse=window.in_use)
                        t0 = time.monotonic()
                        cb = upload_narrowed(table)
                        t1 = time.monotonic()
                        h2d_spans.append((t0, t1))
                        telemetry.record_interval("h2d", t0, t1,
                                                  query_id=qid)
                        sb = catalog.add_batch(cb)
                        if not cq_put((idx, unit, sb, admitted)):
                            sb.close()
                            window.release(admitted)
                            return
                except BaseException as e:  # noqa: BLE001 - surfaced
                    cq_put(e)

        up_thread = threading.Thread(target=uploader, daemon=True,
                                     name="stream-upload")
        pending_close: List = []
        streamed = 0
        sem.get().acquire_if_necessary(ctx.task_id)
        try:
            prefetcher.start()
            up_thread.start()
            build_args = self._prepare_builds(sp, ctx)
            while True:
                cancellation.check_current()
                item = compute_q.get()
                if item is upload_done:
                    break
                if isinstance(item, BaseException):
                    raise item
                idx, unit, sb, admitted = item
                if sb is None:
                    retired[_unit_key(unit)] = _empty_table(
                        chain_top.schema)
                    continue
                pending_close.append((sb, admitted))
                out_table = self._consume_slot(sp, sb, build_args,
                                               compute_spans, qid)
                pending_close.pop()
                sb.close()
                window.release(admitted)
                retired[_unit_key(unit)] = out_table
                streamed += 1
                telemetry.record_stream(
                    query_id=qid, partitionsStreamed=1)
                obs_events.emit("stream.partition",
                                unit=f"{unit.path}:{unit.row_groups}",
                                rows=out_table.num_rows,
                                bytes=out_table.nbytes,
                                retired=len(retired))
            ordered = [retired[_unit_key(u)] for u in units]
            result = self._finish(sp, ordered)
        finally:
            prefetcher.abandon()
            window.abort()
            up_thread.join(timeout=5.0)
            prefetcher.join()
            self._drain(compute_q, pending_close)
            sem.get().release_if_necessary(ctx.task_id)
        frac = _overlap(h2d_spans, compute_spans)
        telemetry.record_stream(query_id=qid,
                                windowPeakBytes=window.peak,
                                recoveries=1 if resumed else 0)
        obs_events.emit("stream.end", partitions=len(units),
                        retired=len(retired),
                        recoveries=1 if resumed else 0,
                        windowPeakBytes=window.peak,
                        overlapFraction=frac)
        return result

    @staticmethod
    def _drain(compute_q, pending_close) -> None:
        """Unwind path: close every slot still registered with the
        catalog (queued for compute, or mid-compute when the chain
        raised) so the spill ledger ends leak-free."""
        for sb, _ in pending_close:
            try:
                sb.close()
            except Exception:
                pass
        pending_close.clear()
        while True:
            try:
                item = compute_q.get_nowait()
            except queue.Empty:
                return
            if isinstance(item, tuple) and len(item) == 4 and \
                    item[2] is not None:
                try:
                    item[2].close()
                except Exception:
                    pass

    # ------------------------------------------------- chain compute

    def _prepare_builds(self, sp: StreamPlan, ctx) -> dict:
        """Materialize every broadcast build side in the chain ONCE
        (window-fitting by planner construction: build sides are
        broadcast children, small by the same planner rule that chose
        a broadcast join)."""
        from spark_rapids_tpu.exec.joins import TpuBroadcastHashJoinExec

        builds = {}
        for node in sp.chain:
            if isinstance(node, TpuBroadcastHashJoinExec):
                builds[id(node)] = node._broadcast_build_table(ctx)
        return builds

    def _consume_slot(self, sp: StreamPlan, sb, build_args,
                      compute_spans, qid) -> pa.Table:
        """Run the operator chain over one window slot and retire the
        result to host. stream.window_evict chaos spills the slot
        before compute touches it, proving the unspill-on-use round
        trip; device.fatal at the stream.dispatch guard classifies a
        dead backend and fences (DeviceLostError rides up)."""
        from spark_rapids_tpu.columnar.arrow_bridge import device_to_arrow
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime import device_monitor, faults
        from spark_rapids_tpu.runtime.memory import get_catalog

        catalog = get_catalog()
        if faults.should_inject("stream.window_evict"):
            from spark_rapids_tpu.runtime.memory import SpillTier

            # the catalog's own spill path, not a raw _to_host(): the
            # device reservation and host-pageable ledger must move
            # with the bytes or the eviction leaks pool.reserved
            with catalog._lock:
                if sb._tier == SpillTier.DEVICE:
                    catalog._spill_one(sb)
            obs_events.emit("stream.window", action="spill",
                            bytes=sb.size_bytes, inUse=None)
        t0 = time.monotonic()
        with device_monitor.guard("stream.dispatch", inject=True):
            batch = sb.get_batch()  # unspills an evicted slot
            out = self._run_chain(sp, batch, build_args)
            out_table = (device_to_arrow(out) if out is not None
                         else _empty_table(sp.chain_top.schema))
        t1 = time.monotonic()
        compute_spans.append((t0, t1))
        telemetry.record_interval("compute", t0, t1, query_id=qid)
        return out_table

    def _run_chain(self, sp: StreamPlan, batch, build_args):
        """One unit through the streamable chain. Returns the chain
        top's device batch, or None when the unit vanishes (filtered
        out / no probe matches)."""
        from spark_rapids_tpu.exec.joins import TpuBroadcastHashJoinExec
        from spark_rapids_tpu.exec.operators import (
            TpuCoalesceBatchesExec,
            TpuFilterExec,
            TpuHashAggregateExec,
            TpuProjectExec,
        )
        from spark_rapids_tpu.expr.ansicheck import raise_if_set

        out = batch
        for node in sp.chain:
            if out is None:
                return None
            if isinstance(node, TpuCoalesceBatchesExec):
                continue  # identity: units are already window-sized
            if isinstance(node, TpuFilterExec):
                if node._ansi_jit is not None:
                    raise_if_set(node._ansi_jit(out))
                out = node._run_jit(out)
            elif isinstance(node, TpuProjectExec):
                if node._ansi_jit is not None:
                    raise_if_set(node._ansi_jit(out))
                out = node._jitted(out)
            elif isinstance(node, TpuBroadcastHashJoinExec):
                build, bt = build_args[id(node)]
                out = node._join_batches([out], build, prepared_bt=bt)
            elif isinstance(node, TpuHashAggregateExec):
                if node._ansi_jit is not None:
                    raise_if_set(node._ansi_jit(out))
                out = node._jit_partial(out)
            else:  # planner admitted it; this executor must know it
                from spark_rapids_tpu.stream.planner import (
                    StreamCompileError,
                )

                raise StreamCompileError(
                    f"no streaming lowering for {type(node).__name__}")
        return out

    # ------------------------------------------------------- finish

    def _finish(self, sp: StreamPlan,
                ordered: List[pa.Table]) -> List[pa.Table]:
        """Device-side finish while the stream's permit is still held:
        a complete-mode agg chain top collapses every retired partial
        into one final table. Returns the partition tables that stand
        in for the chain top."""
        from spark_rapids_tpu.exec.operators import TpuHashAggregateExec

        top = sp.chain_top
        if isinstance(top, TpuHashAggregateExec) and \
                top.mode == "complete":
            return [self._merge_complete(top, ordered)]
        return ordered

    def _merge_complete(self, node, ordered: List[pa.Table]) -> pa.Table:
        """complete-mode agg: every unit retired PARTIAL buffers; one
        merge+finalize over their concatenation yields the final rows
        (operators.py _merge_final — associative by construction)."""
        from spark_rapids_tpu.columnar.arrow_bridge import (
            arrow_to_device,
            device_to_arrow,
        )

        good = [t for t in ordered if t.num_rows > 0]
        if not good:
            if not node.grouping:
                return device_to_arrow(node._empty_global_result())
            return _empty_table(node.schema)
        merged = pa.concat_tables(good, promote_options="none")
        return device_to_arrow(node._jit_merge(arrow_to_device(merged)))

    def _run_remainder(self, sp: StreamPlan, ordered: List[pa.Table],
                       phys) -> pa.Table:
        """Substitute retired partitions for the chain top and run the
        surrounding plan on the ordinary eager engine. The parent's
        child list is restored even on failure — the plan object is
        also the dispatch ladder's fallback input."""
        top = sp.chain_top
        idx = sp.parent.children.index(top)
        sp.parent.children[idx] = StreamedSourceExec(
            ordered, top.schema, self.conf)
        try:
            return phys.collect()
        finally:
            sp.parent.children[idx] = top


def scan_batch_rows(scan) -> int:
    return scan._batch_rows


def _overlap(a_spans, b_spans) -> Optional[float]:
    from spark_rapids_tpu.obs.telemetry import _overlap_fraction

    f = _overlap_fraction(a_spans, b_spans)
    return round(f, 4) if f is not None else None
