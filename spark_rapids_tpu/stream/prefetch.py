"""Host-side parquet prefetcher for the streaming executor.

N reader threads (spark.rapids.tpu.stream.prefetch.threads) decode
ScanUnits (io/readers.py split_scan_units: row-group-granular,
stats-pruned, packed to ~window/4 bytes) into ONE bounded staging
queue, riding the same abandoned-Event discipline as the
multithreaded eager reader (io/readers.py read_parquet_multithreaded):
a consumer that stops pulling unblocks every producer promptly, and
file opens retry transient I/O faults through the io.read backoff
site.

Chaos site `stream.prefetch` fires INSIDE a worker around a unit's
decode: the unit is re-enqueued onto the shared work queue (bounded
per-unit retries) and the stream continues — partition-granular retry
without restarting the query. Exhausted retries and real decode
errors surface to the consumer through the staging queue as the
exception itself, preserving the pipeline's ordering guarantees
(everything staged before the error is still consumable).
"""

from __future__ import annotations

import queue
import threading
from typing import List, Optional

import pyarrow as pa

from spark_rapids_tpu.io import readers

#: staging-queue item marking all units drained (every worker exited)
PREFETCH_DONE = object()

#: per-unit budget for stream.prefetch chaos re-enqueues
_UNIT_RETRIES = 3


class Prefetcher:
    """Decode `units` into `staging` from a pool of reader threads.

    Items on `staging` are (unit_index, unit, pa.Table) tuples, an
    Exception instance (fatal — consumer should raise), or
    PREFETCH_DONE (exactly once, after the last unit). One unit decodes
    to ONE concatenated host table: unit size is already bounded to a
    fraction of the device window, and unit-granular staging is what
    makes retirement lineage (mid-stream recovery) partition-exact."""

    def __init__(self, units: List[readers.ScanUnit],
                 columns: Optional[List[str]], batch_rows: int,
                 num_threads: int,
                 read_dictionary: Optional[List[str]] = None,
                 cancel_token=None):
        self._columns = columns
        self._batch_rows = batch_rows
        self._read_dictionary = read_dictionary
        self._cancel_token = cancel_token
        self._work: "queue.Queue" = queue.Queue()
        for i, u in enumerate(units):
            self._work.put((i, u, 0))  # (index, unit, retry_count)
        self._remaining = len(units)
        self._rlock = threading.Lock()
        self.abandoned = threading.Event()
        nthreads = max(1, min(int(num_threads), max(1, len(units))))
        self.staging: "queue.Queue" = queue.Queue(maxsize=2 * nthreads)
        self._done_emitted = threading.Event()
        self._threads = [
            threading.Thread(target=self._worker, daemon=True,
                             name=f"stream-prefetch-{i}")
            for i in range(nthreads)]

    def start(self) -> None:
        for t in self._threads:
            t.start()
        if not self._threads:
            self._emit_done()

    def abandon(self) -> None:
        """Consumer is leaving (error, cancel, device loss): unblock
        every producer; staged tables are garbage-collected."""
        self.abandoned.set()

    def join(self, timeout_s: float = 5.0) -> None:
        for t in self._threads:
            t.join(timeout=timeout_s)

    # --- internals ---

    def _emit_done(self) -> None:
        if not self._done_emitted.is_set():
            self._done_emitted.set()
            self._put(PREFETCH_DONE)

    def _put(self, item) -> bool:
        while not self.abandoned.is_set():
            try:
                self.staging.put(item, timeout=0.2)
                return True
            except queue.Full:
                continue
        return False

    def _decode(self, unit: readers.ScanUnit) -> pa.Table:
        parts = [t for t in readers.read_scan_unit(
            unit, self._columns, self._batch_rows,
            read_dictionary=self._read_dictionary)]
        if not parts:
            # stats-pruned-to-empty unit: stage a zero-row table so the
            # retirement ledger still covers it
            schema = readers._open_retry(
                lambda: readers.pq.read_schema(unit.path), unit.path)
            empty = schema.empty_table()
            return empty if self._columns is None \
                else empty.select(self._columns)
        return pa.concat_tables(parts, promote_options="none")

    def _worker(self) -> None:
        from spark_rapids_tpu.runtime import cancellation, faults

        with cancellation.scope(self._cancel_token):
            while not self.abandoned.is_set():
                try:
                    idx, unit, tries = self._work.get(timeout=0.1)
                except queue.Empty:
                    with self._rlock:
                        if self._remaining == 0:
                            self._emit_done()
                            return
                    continue
                try:
                    faults.maybe_inject("stream.prefetch",
                                        detail=unit.path)
                    table = self._decode(unit)
                except faults.InjectedFault as e:
                    if tries + 1 >= _UNIT_RETRIES:
                        self._put(e)
                        return
                    # partition-granular retry: the unit goes back on
                    # the shared work queue; any worker may pick it up
                    self._work.put((idx, unit, tries + 1))
                    continue
                except BaseException as e:  # noqa: BLE001 - surfaced
                    self._put(e)
                    return
                if not self._put((idx, unit, table)):
                    return
                with self._rlock:
                    self._remaining -= 1
                    last = self._remaining == 0
                if last:
                    self._emit_done()
                    return
