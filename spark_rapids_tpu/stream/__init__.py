"""Out-of-core streaming executor (spark.rapids.tpu.stream.*).

Partition-granular pipeline for scans whose working set exceeds the
device window quota: prefetch -> double-buffered H2D upload into a
bounded device window -> streamable operator chain, with retirement
lineage for mid-stream device-loss resume. See stream/executor.py.
"""

from spark_rapids_tpu.stream.executor import (
    StreamedSourceExec,
    StreamExecutor,
)
from spark_rapids_tpu.stream.planner import (
    StreamCompileError,
    StreamPlan,
    plan_stream,
    stamp_stream_strategy,
    stream_selected,
)
from spark_rapids_tpu.stream.window import DeviceWindow, window_budget

__all__ = [
    "DeviceWindow",
    "StreamCompileError",
    "StreamedSourceExec",
    "StreamExecutor",
    "StreamPlan",
    "plan_stream",
    "stamp_stream_strategy",
    "stream_selected",
    "window_budget",
]
