"""Streaming strategy selection + chain extraction.

The out-of-core streaming executor (stream/executor.py) runs a
partition-granular pipeline over ONE oversized parquet scan: prefetch
threads decode row-group units into a host staging queue, a
double-buffered uploader fills a bounded device window, and the chain
of streamable operators above the scan consumes window slots one unit
at a time. This module decides WHEN that engine engages and WHICH
prefix of the physical plan it can stream.

Selection mirrors the fused engine's working-set gate
(exec/fused.py _scan_parts: file bytes x ~6 decode/pad expansion vs
the HBM budget) but inverts it: where fused REFUSES a scan whose
working set exceeds HBM, streaming VOLUNTEERS for a scan whose
estimated decoded bytes exceed `window.quotaFraction` of FREE HBM —
exactly the queries the resident engines would either OOM on or
demote to the dispatch-bound eager path batch by batch.

The streamable chain is the maximal plan prefix above the scan where
every operator consumes exactly the streamed child's batches with no
cross-batch state EXCEPT a terminal partial/complete aggregation
(whose merge phase is associative over retired partials) and
broadcast joins whose build side fits the window (materialized once,
probed per unit). Anything else (sorts, shuffles, final aggs over
other inputs) terminates the chain; retired partitions substitute for
the chain top and the ordinary engines run the remainder.
"""

from __future__ import annotations

import os
from typing import List, Optional, Tuple

#: join types where probing one streamed batch against the broadcast
#: build side is independent of every other batch (no build-side
#: tracking as full/right outer would need; existence rides the
#: probe-side semantics)
STREAM_JOIN_TYPES = ("inner", "left", "left_semi", "left_anti",
                     "existence")

#: decoded-working-set expansion over on-disk parquet bytes — the same
#: heuristic constant as the fused engine's scan gate (decode +
#: capacity padding + operator temporaries)
DECODE_EXPANSION = 6


class StreamCompileError(NotImplementedError):
    """Plan (or this scan) has no streaming lowering — structural, so
    dispatch records a fallback, not a degradation."""


class StreamPlan:
    """One selected scan + the streamable operator chain above it.

    `chain` is bottom-up and EXCLUDES the scan; empty means the scan's
    own batches retire directly. `parent` is the node whose child list
    contains the chain top (None when the chain top is the plan root,
    in which case retired partitions concatenate into the result)."""

    def __init__(self, scan, chain: List, parent, est_bytes: int):
        self.scan = scan
        self.chain = chain
        self.parent = parent
        self.est_bytes = est_bytes

    @property
    def chain_top(self):
        return self.chain[-1] if self.chain else self.scan


def _scan_files(scan) -> List[str]:
    return [f for task in scan._tasks for f in task]


def estimate_scan_bytes(scan) -> int:
    total = 0
    for f in _scan_files(scan):
        try:
            total += os.path.getsize(f)
        except OSError:
            pass
    return total


def free_hbm() -> int:
    """HBM not currently reserved by resident queries — the pool the
    window budget is carved from."""
    from spark_rapids_tpu.runtime.memory import get_catalog

    pool = get_catalog().pool
    return max(0, pool.limit - pool.reserved)


def _eligible_scans(phys) -> List:
    """Parquet device scans the streaming reader can drive: row-group
    addressable (no hive partition-value injection, no lakehouse
    delete-set semantics) with at least one file."""
    from spark_rapids_tpu.exec.operators import TpuFileScanExec

    out = []

    def walk(node):
        if (isinstance(node, TpuFileScanExec) and node.is_tpu
                and node.fmt == "parquet" and node._part_spec is None
                and _scan_files(node)):
            out.append(node)
        for c in node.children:
            walk(c)

    walk(phys)
    return out


def select_scan(phys, conf) -> Optional[Tuple]:
    """The largest eligible scan whose estimated decoded working set
    exceeds the window quota fraction of free HBM, or None when every
    scan fits residently (the resident engines are strictly faster
    when the table fits — streaming only pays off out of core)."""
    from spark_rapids_tpu.config import rapids_conf as rc

    scans = _eligible_scans(phys)
    if not scans:
        return None
    sized = sorted(((estimate_scan_bytes(s), s) for s in scans),
                   key=lambda p: -p[0])
    est, scan = sized[0]
    frac = conf.get(rc.STREAM_WINDOW_QUOTA_FRACTION)
    if est * DECODE_EXPANSION <= frac * free_hbm():
        return None
    return est, scan


def stream_selected(phys, conf) -> bool:
    """Cheap dispatch-time gate (no plan mutation)."""
    return select_scan(phys, conf) is not None


def _parent_map(phys) -> dict:
    parents = {}

    def walk(node):
        for c in node.children:
            parents[id(c)] = node
            walk(c)

    walk(phys)
    return parents


def _streamable_parent(parent, child) -> Optional[str]:
    """Is `parent` streamable over `child`'s batches? Returns
    "extend" (keep walking up), "terminal" (include, then stop), or
    None (chain stops below `parent`)."""
    from spark_rapids_tpu.exec.joins import TpuBroadcastHashJoinExec
    from spark_rapids_tpu.exec.operators import (
        TpuCoalesceBatchesExec,
        TpuFilterExec,
        TpuHashAggregateExec,
        TpuProjectExec,
    )

    if isinstance(parent, (TpuFilterExec, TpuProjectExec,
                           TpuCoalesceBatchesExec)):
        return "extend"
    if isinstance(parent, TpuBroadcastHashJoinExec):
        # only the PROBE side streams; the build side must be the
        # broadcast child so it materializes once per query
        if (parent.children and parent.children[0] is child
                and parent.join_type in STREAM_JOIN_TYPES):
            return "extend"
        return None
    if isinstance(parent, TpuHashAggregateExec):
        # partial: per-unit update, retire buffer rows (the shuffle
        # above merges). complete: per-unit update + ONE merge/finalize
        # over all retired partials inside the executor. final mode
        # consumes post-shuffle buffers — not this scan's stream.
        if parent.children[0] is child and parent.mode in (
                "partial", "complete"):
            return "terminal"
        return None
    return None


def plan_stream(phys, conf) -> StreamPlan:
    """Select the scan and extract its maximal streamable chain.
    Raises StreamCompileError when no scan qualifies."""
    sel = select_scan(phys, conf)
    if sel is None:
        raise StreamCompileError(
            "no out-of-core parquet scan in this plan "
            "(every scan's working set fits resident HBM)")
    est, scan = sel
    parents = _parent_map(phys)
    chain: List = []
    cur = scan
    while True:
        parent = parents.get(id(cur))
        if parent is None:
            break
        kind = _streamable_parent(parent, cur)
        if kind is None:
            break
        chain.append(parent)
        cur = parent
        if kind == "terminal":
            break
    top = chain[-1] if chain else scan
    return StreamPlan(scan, chain, parents.get(id(top)), est)


def stamp_stream_strategy(phys, conf) -> None:
    """explain() support: mark the selected scan so pretty() renders
    `TpuFileScanExec [strategy=stream]` — the streaming twin of the
    mesh planner's stamp_exchange_strategies."""
    sel = select_scan(phys, conf)
    if sel is not None:
        sel[1].stream_strategy = "stream"
