"""Bounded device window — the HBM budget a streaming query may hold.

The window is the streaming executor's admission unit: the uploader
blocks in `admit()` until the in-flight device bytes fit the budget,
compute releases a slot's bytes when its unit retires, and the peak
high-water mark feeds telemetry (`windowPeakBytes`) and the
window-bounded CI assertion. Single-condition-variable accounting:
slots are admitted in arrival order, which is exactly the pipeline's
unit order.

Budget derivation (`window_budget`): quotaFraction x free HBM, capped
by `stream.window.maxBytes` when set and by the per-query device
quota (runtime/memory.py SpillCatalog.query_quota_bytes) so a
streaming query charges the SAME ledger as a resident one — then
scaled by the admission priority class: a negative-priority `batch`
tenant gets HALF a window, so a 10x-HBM batch stream cannot starve
`interactive` queries of upload bandwidth or HBM headroom.
"""

from __future__ import annotations

import threading

#: never derive a window below this — a single capacity bucket of a
#: narrow batch; below it the stream would thrash on per-row uploads
MIN_WINDOW_BYTES = 64 * 1024


def window_budget(conf, priority: int = 0) -> int:
    """Derive this query's window byte budget (see module doc)."""
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.runtime.memory import get_catalog

    cat = get_catalog()
    free = max(0, cat.pool.limit - cat.pool.reserved)
    budget = int(free * conf.get(rc.STREAM_WINDOW_QUOTA_FRACTION))
    max_bytes = conf.get(rc.STREAM_WINDOW_MAX_BYTES)
    if max_bytes > 0:
        budget = min(budget, max_bytes)
    if cat.query_quota_bytes > 0:
        budget = min(budget, cat.query_quota_bytes)
    if priority < 0:
        # batch-class tenants ride half a window (serve admission
        # SERVE_PRIORITY_CLASSES: interactive=100, standard=0,
        # batch=-100)
        budget //= 2
    return max(budget, MIN_WINDOW_BYTES)


class StreamAborted(RuntimeError):
    """The window was aborted while a thread waited for admission —
    the pipeline is unwinding (error, cancel, or device loss)."""


class DeviceWindow:
    """Condition-variable byte window with peak tracking."""

    def __init__(self, budget_bytes: int):
        self.budget = max(1, int(budget_bytes))
        self._cv = threading.Condition()
        self.in_use = 0
        self.peak = 0
        self._aborted = False

    def admit(self, nbytes: int, poll_s: float = 0.2) -> int:
        """Block until `nbytes` fits the window (an EMPTY window always
        admits, so one unit larger than the whole budget still makes
        progress — estimate slack must not wedge the stream). Returns
        the admitted byte count; raises StreamAborted if abort() lands
        while waiting. Polls so the executor's cancellation check in
        the waiter's loop stays responsive."""
        from spark_rapids_tpu.runtime import cancellation

        nbytes = max(0, int(nbytes))
        with self._cv:
            while True:
                if self._aborted:
                    raise StreamAborted("window aborted")
                if self.in_use == 0 or self.in_use + nbytes <= self.budget:
                    self.in_use += nbytes
                    self.peak = max(self.peak, self.in_use)
                    return nbytes
                self._cv.wait(timeout=poll_s)
                # a cancelled query must not keep waiting for slots the
                # compute side will never release
                cancellation.check_current()

    def release(self, nbytes: int) -> None:
        with self._cv:
            self.in_use = max(0, self.in_use - max(0, int(nbytes)))
            self._cv.notify_all()

    def abort(self) -> None:
        """Unblock every admit() waiter with StreamAborted."""
        with self._cv:
            self._aborted = True
            self._cv.notify_all()
