"""Stretch (dry-run): route window slots across the device mesh.

Gated by `spark.rapids.tpu.stream.mesh.enabled` (default false). The
full design — each ScanUnit's window slot uploaded to a distinct mesh
device and consumed by the SPMD engine's per-device shards
(parallel/plan_compiler.py) — needs the mesh engine's exchange
planner to accept externally-placed shards; until then this module
emits the PLACEMENT PLAN ONLY: one `stream.window` event with
action="mesh" per unit, carrying the device each slot WOULD land on
(round-robin over the local mesh), and moves no data. CI and the
event log can therefore already validate slot->device fan-out shape
against the future router.
"""

from __future__ import annotations

from typing import List

from spark_rapids_tpu.io import readers


def plan_mesh_slots(units: List[readers.ScanUnit]) -> List[int]:
    """Dry-run placement: unit i -> device (i mod n_devices). Emits
    one stream.window(action="mesh") event per unit; returns the
    device index per unit for tests."""
    import jax

    from spark_rapids_tpu.obs import events as obs_events

    try:
        n = max(1, len(jax.devices()))
    except Exception:
        n = 1
    placement = []
    for i, u in enumerate(units):
        dev = i % n
        placement.append(dev)
        obs_events.emit("stream.window", action="mesh",
                        bytes=u.est_bytes, inUse=dev)
    return placement
