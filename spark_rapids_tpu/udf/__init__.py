from spark_rapids_tpu.udf.compiler import (  # noqa: F401
    UdfCompileError,
    compile_udf,
)
from spark_rapids_tpu.udf.pyudf import PythonUDF  # noqa: F401
