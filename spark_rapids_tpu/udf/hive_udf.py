"""Hive UDF surface + session UDF registry — the hiveUDFs.scala /
UDFRegistration analog.

The reference runs Hive `GenericUDF`s as black-box row functions on the
CPU plan UNLESS the UDF also implements `RapidsUDF.evaluateColumnar`,
in which case it runs on device inside the columnar pipeline
(org/apache/spark/sql/hive/rapids/hiveUDFs.scala;
sql-plugin-api/.../RapidsUDF.java:22-68). The same dual contract here:

    class MyUdf(HiveGenericUDF):
        def initialize(self, arg_types):    # -> result DataType
            return double
        def evaluate(self, x, y):           # per-row python values
            return x * y
        # OPTIONAL device path (RapidsUDF role): jnp arrays in/out,
        # traced into the enclosing XLA program; arguments arrive as
        # all value arrays then all validity arrays (DeviceUDF order)
        def evaluate_columnar(self, x, y, xv, yv):
            return x * y, xv & yv

    spark.udf.registerHive("my_udf", MyUdf())
    df.select(F.call_udf("my_udf", df.a, df.b))

`spark.udf.register(name, fn, returnType)` covers plain Python
functions (attempted through the bytecode compiler first, like
F.udf)."""

from __future__ import annotations

from typing import Dict, Optional

from spark_rapids_tpu.sqltypes import DataType
from spark_rapids_tpu.sqltypes.datatypes import double


class HiveSimpleUDF:
    """evaluate(*row_values) -> value; fixed returnType attribute."""

    returnType: DataType = double

    def evaluate(self, *args):
        raise NotImplementedError


class HiveGenericUDF(HiveSimpleUDF):
    """Adds Hive's initialize(arg_types) -> result type negotiation."""

    def initialize(self, arg_types) -> DataType:
        return self.returnType


class UDFRegistration:
    """session.udf — named registration so SQL-ish call sites
    (F.call_udf) resolve by name."""

    def __init__(self, session):
        self._session = session
        self._named: Dict[str, object] = {}

    def register(self, name: str, fn=None, returnType=None):
        """Plain Python function: compiled to device expressions when
        the bytecode compiler can, rowwise host fallback otherwise
        (same pipeline as F.udf)."""
        from spark_rapids_tpu.api import functions as F

        wrapped = F.udf(fn, returnType=returnType)
        self._named[name] = wrapped
        return wrapped

    def registerHive(self, name: str, instance: HiveSimpleUDF):
        self._named[name] = instance
        return instance

    def registerDevice(self, name: str, fn, returnType: DataType):
        """Direct RapidsUDF-style columnar device function:
        fn(values..., validities...) -> (values, validity)."""
        self._named[name] = ("device", fn, returnType)
        return fn

    def lookup(self, name: str):
        if name not in self._named:
            raise KeyError(f"UDF {name!r} is not registered")
        return self._named[name]


def call_registered(session, name: str, cols):
    """Build the Column for a registered UDF (F.call_udf body)."""
    from spark_rapids_tpu.api.column import Column
    from spark_rapids_tpu.api.functions import expr_of
    from spark_rapids_tpu.expr.deviceudf import DeviceUDF
    from spark_rapids_tpu.udf.pyudf import PythonUDF

    entry = session.udf.lookup(name)
    exprs = [expr_of(c) for c in cols]
    if isinstance(entry, tuple) and entry[0] == "device":
        _, fn, rtype = entry
        return Column(DeviceUDF(fn, rtype, exprs), name)
    if isinstance(entry, HiveSimpleUDF):
        def _dt(e):
            try:
                return e.dtype
            except AttributeError:
                return None  # unresolved column: type known at binding

        rtype = (entry.initialize([_dt(e) for e in exprs])
                 if isinstance(entry, HiveGenericUDF)
                 else entry.returnType)
        columnar = getattr(entry, "evaluate_columnar", None)
        if columnar is not None:
            # the RapidsUDF dual interface: device columnar evaluation
            # fused into the enclosing program
            return Column(DeviceUDF(columnar, rtype, exprs), name)
        return Column(PythonUDF(entry.evaluate, exprs, rtype,
                                name=name), name)
    # F.udf-wrapped callable
    return entry(*cols)
