"""UDF bytecode compiler: CPython bytecode -> expression IR.

The analog of the reference's udf-compiler module
(`udf-compiler/src/main/scala/com/nvidia/spark/udf/
CatalystExpressionBuilder.scala:45`, `CFG.scala:138`,
`Instruction.scala`): the reference abstract-interprets JVM bytecode of
Scala lambdas over a symbolic operand stack and emits Catalyst
expressions so the UDF runs as native device kernels instead of a
black-box JVM call. Same design here for Python: symbolically execute
the function's bytecode with arguments bound to engine expressions;
control flow (ternaries, and/or, early returns, `is None` guards)
branches the executor and merges as `If` expressions at RETURN.

Unsupported constructs raise UdfCompileError and the UDF falls back to
rowwise host execution (udf/pyudf.py) — mirroring the reference's
opt-in fallback (`LogicalPlanRules.scala`).

Known semantic deltas (documented, same class of caveats as the
reference's compiler): int64 wraparound vs Python bigints; `1/0` is
NULL, not ZeroDivisionError; unguarded None inputs null-propagate
instead of raising TypeError.
"""

from __future__ import annotations

import dis
import sys
from typing import Any, Dict, List

from spark_rapids_tpu.expr import (
    Abs, Add, And, BRound, Cast, Concat, Divide, EndsWith,
    EqualTo, GreaterThan, GreaterThanOrEqual, Greatest, If, In,
    IntegralDivide, IsNull, Least, Length, LessThan, LessThanOrEqual,
    Literal, Lower, Multiply, Not, Or, Pow, Remainder,
    ShiftLeft, ShiftRight,
    StartsWith, StringReplace, StringTrim, StringTrimLeft,
    StringTrimRight, Subtract, UnaryMinus, Upper,
)
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.expr.mathexpr import (
    BitwiseAnd, BitwiseNot, BitwiseOr, BitwiseXor,
)
from spark_rapids_tpu.sqltypes import (
    BooleanType, IntegralType, StringType,
)
from spark_rapids_tpu.sqltypes.datatypes import (
    boolean, double, long, string,
)

MAX_BRANCHES = 64

_NULL = object()   # PUSH_NULL / LOAD_GLOBAL-NULL sentinel
_SELF = object()   # folded-self marker under a _BoundMethod


class UdfCompileError(Exception):
    pass


class _Module:
    def __init__(self, name):
        self.name = name

    def __repr__(self):
        return f"<module {self.name}>"


class _BoundMethod:
    def __init__(self, target: Expression, name: str):
        self.target = target
        self.name = name

    def __repr__(self):
        return f"<method .{self.name}>"


_MATH_FNS = {
    "sqrt": "Sqrt", "exp": "Exp", "log": "Log", "log10": "Log10",
    "log2": "Log2", "sin": "Sin", "cos": "Cos", "tan": "Tan",
    "asin": "Asin", "acos": "Acos", "atan": "Atan", "sinh": "Sinh",
    "cosh": "Cosh", "tanh": "Tanh", "floor": "Floor", "ceil": "Ceil",
    "fabs": "Abs", "pow": "Pow", "atan2": "Atan2", "hypot": "Hypot",
    "degrees": "ToDegrees", "radians": "ToRadians",
}

_BUILTINS = ("abs", "min", "max", "len", "round", "float", "int", "str",
             "bool")


def _lift(v) -> Expression:
    if isinstance(v, Expression):
        return v
    if v is _NULL or v is _SELF or isinstance(v, (_Module, _BoundMethod,
                                                  tuple)):
        raise UdfCompileError(f"cannot use {v!r} as a value")
    return Literal(v)


def _binary(op: str, a, b):
    if not isinstance(a, Expression) and not isinstance(b, Expression):
        return {"+": lambda: a + b, "-": lambda: a - b,
                "*": lambda: a * b, "/": lambda: a / b,
                "//": lambda: a // b, "%": lambda: a % b,
                "**": lambda: a ** b, "&": lambda: a & b,
                "|": lambda: a | b, "^": lambda: a ^ b,
                "<<": lambda: a << b, ">>": lambda: a >> b}[op]()
    a, b = _lift(a), _lift(b)
    if op == "+":
        if isinstance(a.dtype, StringType) or isinstance(b.dtype,
                                                         StringType):
            return Concat(a, b)
        return Add(a, b)
    if op == "-":
        return Subtract(a, b)
    if op == "*":
        return Multiply(a, b)
    if op == "/":
        # Python / is always true division
        return Divide(Cast(a, double), Cast(b, double))
    if op == "//":
        if (isinstance(a.dtype, IntegralType) and
                isinstance(b.dtype, IntegralType)):
            # Python floors toward -inf for EITHER divisor sign; Spark
            # IntegralDivide truncates toward zero. q_floor = q_trunc - 1
            # when a nonzero remainder disagrees in sign with b.
            q = IntegralDivide(a, b)
            r = Remainder(a, b)
            needs_fix = And(
                Not(EqualTo(r, Literal(0, long))),
                Not(EqualTo(LessThan(r, Literal(0, long)),
                            LessThan(b, Literal(0, long)))))
            return If(needs_fix, Subtract(q, Literal(1, long)), q)
        raise UdfCompileError("float // unsupported")
    if op == "%":
        # Python % takes the sign of the divisor; the engine's Remainder
        # is Java-truncated (sign of dividend). Correct with r_trunc + b
        # when a nonzero truncated remainder disagrees in sign with b —
        # for both integral and floating operands (Pmod would diverge
        # from Python whenever b < 0).
        if (isinstance(a.dtype, IntegralType) and
                isinstance(b.dtype, IntegralType)):
            zero = Literal(0, long)
        else:
            zero = Literal(0.0, double)
        r = Remainder(a, b)
        needs_fix = And(
            Not(EqualTo(r, zero)),
            Not(EqualTo(LessThan(r, zero), LessThan(b, zero))))
        return If(needs_fix, Add(r, b), r)
    if op == "**":
        return Pow(Cast(a, double), Cast(b, double))
    if op == "&":
        return BitwiseAnd(a, b)
    if op == "|":
        return BitwiseOr(a, b)
    if op == "^":
        return BitwiseXor(a, b)
    if op == "<<":
        return ShiftLeft(a, b)
    if op == ">>":
        return ShiftRight(a, b)
    raise UdfCompileError(f"binary op {op!r} unsupported")


def _compare(op: str, a, b):
    if not isinstance(a, Expression) and not isinstance(b, Expression):
        return {"<": a < b, "<=": a <= b, "==": a == b, "!=": a != b,
                ">": a > b, ">=": a >= b}[op]
    a, b = _lift(a), _lift(b)
    table = {"<": LessThan, "<=": LessThanOrEqual, "==": EqualTo,
             ">": GreaterThan, ">=": GreaterThanOrEqual}
    if op in table:
        return table[op](a, b)
    if op == "!=":
        return Not(EqualTo(a, b))
    raise UdfCompileError(f"compare {op!r} unsupported")


def _truthy(e: Expression) -> Expression:
    """Python truthiness of a column expression as a boolean expr."""
    from spark_rapids_tpu.sqltypes import NumericType

    if isinstance(e.dtype, BooleanType):
        return e
    if isinstance(e.dtype, NumericType):
        zero = Literal(0.0 if not isinstance(e.dtype, IntegralType)
                       else 0, e.dtype)
        return Not(EqualTo(e, zero))
    if isinstance(e.dtype, StringType):
        return GreaterThan(Length(e), Literal(0))
    raise UdfCompileError(f"truthiness of {e.dtype} unsupported")


def _const_str(v) -> str:
    if isinstance(v, Literal) and isinstance(v.value, str):
        return v.value
    if isinstance(v, str):
        return v
    raise UdfCompileError("string-method argument must be constant")


class _Compiler:
    def __init__(self, fn, args: List[Expression]):
        if sys.version_info[:2] != (3, 12):
            # opcode set + argrepr conventions are 3.12-specific (3.11
            # uses LOAD_METHOD/JUMP_IF_*_OR_POP; 3.13 reorders
            # LOAD_GLOBAL's NULL push) — other versions fall back
            raise UdfCompileError(
                "bytecode compiler targets CPython 3.12")
        self.fn = fn
        code = fn.__code__
        if code.co_argcount != len(args):
            raise UdfCompileError(
                f"udf takes {code.co_argcount} args, got {len(args)}")
        self.cells = {}
        if fn.__closure__:
            self.cells = {
                name: cell.cell_contents
                for name, cell in zip(code.co_freevars, fn.__closure__)}
        self.start_locals: Dict[str, Any] = dict(
            zip(code.co_varnames[:len(args)], args))
        self.instrs = [i for i in dis.get_instructions(fn)
                       if i.opname != "CACHE"]
        self.by_offset = {i.offset: idx
                          for idx, i in enumerate(self.instrs)}
        self.branches = 0

    def compile(self) -> Expression:
        return _lift(self.run(0, [], dict(self.start_locals)))

    # --- the symbolic interpreter loop ---

    def run(self, idx: int, stack: List[Any], local: Dict[str, Any]):
        while idx < len(self.instrs):
            ins = self.instrs[idx]
            op = ins.opname
            if op in ("RESUME", "NOP", "PRECALL", "MAKE_CELL",
                      "COPY_FREE_VARS"):
                pass
            elif op == "LOAD_FAST":
                if ins.argval not in local:
                    raise UdfCompileError(f"unbound local {ins.argval!r}")
                stack.append(local[ins.argval])
            elif op == "STORE_FAST":
                local[ins.argval] = stack.pop()
            elif op == "LOAD_CONST":
                stack.append(ins.argval)
            elif op == "LOAD_DEREF":
                if ins.argval not in self.cells:
                    raise UdfCompileError(
                        f"free variable {ins.argval!r} unsupported")
                stack.append(self.cells[ins.argval])
            elif op == "LOAD_GLOBAL":
                if ins.argrepr.startswith("NULL + "):
                    stack.append(_NULL)
                stack.append(self._global(ins.argval))
            elif op == "PUSH_NULL":
                stack.append(_NULL)
            elif op == "LOAD_ATTR":
                self._load_attr(ins, stack)
            elif op == "BINARY_OP":
                b = stack.pop()
                a = stack.pop()
                stack.append(_binary(ins.argrepr.rstrip("="), a, b))
            elif op == "COMPARE_OP":
                b = stack.pop()
                a = stack.pop()
                stack.append(_compare(ins.argrepr, a, b))
            elif op == "IS_OP":
                b = stack.pop()
                a = stack.pop()
                if not ((a is None) ^ (b is None)):
                    raise UdfCompileError("`is` only supported vs None")
                e = IsNull(_lift(a if b is None else b))
                stack.append(Not(e) if ins.argval == 1 else e)
            elif op == "CONTAINS_OP":
                coll = stack.pop()
                v = stack.pop()
                if isinstance(coll, Expression):
                    raise UdfCompileError(
                        "`in` needs a constant collection")
                if isinstance(v, Expression):
                    if not isinstance(coll, (tuple, list, set,
                                             frozenset)):
                        raise UdfCompileError(
                            "`in` target must be a constant collection")
                    e = In(v, list(coll))  # raw python literal values
                else:
                    e = v in coll
                if ins.argval == 1:
                    e = Not(e) if isinstance(e, Expression) else (not e)
                stack.append(e)
            elif op == "UNARY_NEGATIVE":
                v = stack.pop()
                stack.append(UnaryMinus(v) if isinstance(v, Expression)
                             else -v)
            elif op == "UNARY_NOT":
                v = stack.pop()
                stack.append(Not(_truthy(v))
                             if isinstance(v, Expression) else (not v))
            elif op == "UNARY_INVERT":
                v = stack.pop()
                stack.append(BitwiseNot(v) if isinstance(v, Expression)
                             else ~v)
            elif op == "COPY":
                stack.append(stack[-ins.argval])
            elif op == "SWAP":
                stack[-1], stack[-ins.argval] = (stack[-ins.argval],
                                                 stack[-1])
            elif op == "POP_TOP":
                stack.pop()
            elif op == "CALL":
                self._call(ins.argval, stack)
            elif op in ("POP_JUMP_IF_FALSE", "POP_JUMP_IF_TRUE",
                        "POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
                cond = stack.pop()
                return self._branch(op, cond, idx, ins, stack, local)
            elif op == "JUMP_FORWARD":
                idx = self.by_offset[ins.argval]
                continue
            elif op == "RETURN_VALUE":
                return stack.pop()
            elif op == "RETURN_CONST":
                return ins.argval
            elif op == "JUMP_BACKWARD":
                raise UdfCompileError("loops unsupported")
            else:
                raise UdfCompileError(f"opcode {op} unsupported")
            idx += 1
        raise UdfCompileError("fell off end of bytecode")

    # --- control flow ---

    def _branch(self, op, cond, idx, ins, stack, local):
        self.branches += 1
        if self.branches > MAX_BRANCHES:
            raise UdfCompileError("too many branches")
        jump_idx = self.by_offset[ins.argval]
        next_idx = idx + 1
        if not isinstance(cond, Expression):
            taken = {"POP_JUMP_IF_FALSE": not cond,
                     "POP_JUMP_IF_TRUE": bool(cond),
                     "POP_JUMP_IF_NONE": cond is None,
                     "POP_JUMP_IF_NOT_NONE": cond is not None}[op]
            return self.run(jump_idx if taken else next_idx, stack,
                            local)
        if op in ("POP_JUMP_IF_NONE", "POP_JUMP_IF_NOT_NONE"):
            test = IsNull(cond)
            jump_on_true = op == "POP_JUMP_IF_NONE"
        else:
            test = _truthy(cond)  # Python truthiness (`if s:`, `if n:`)
            jump_on_true = op == "POP_JUMP_IF_TRUE"
        taken = self.run(jump_idx, list(stack), dict(local))
        fallthrough = self.run(next_idx, list(stack), dict(local))
        if jump_on_true:
            t_val, f_val = taken, fallthrough
        else:
            t_val, f_val = fallthrough, taken
        return self._merge(test, t_val, f_val)

    def _merge(self, cond: Expression, t_val, f_val) -> Expression:
        # boolean short-circuits become And/Or instead of If
        if isinstance(cond.dtype, BooleanType):
            if (t_val is True and isinstance(f_val, Expression) and
                    isinstance(f_val.dtype, BooleanType)):
                return Or(cond, f_val)
            if (f_val is False and isinstance(t_val, Expression) and
                    isinstance(t_val.dtype, BooleanType)):
                return And(cond, t_val)
        # a bare None branch takes its type from the sibling branch
        if t_val is None and isinstance(f_val, Expression):
            t_val = Literal(None, f_val.dtype)
        elif f_val is None and isinstance(t_val, Expression):
            f_val = Literal(None, t_val.dtype)
        return If(cond, _lift(t_val), _lift(f_val))

    # --- names / calls ---

    def _global(self, name: str):
        if name in _BUILTINS:
            return ("builtin", name)
        g = self.fn.__globals__.get(name)
        import math as _math

        if g is _math:
            return _Module("math")
        if isinstance(g, (bool, int, float, str)):
            return g  # module-level constant snapshot
        raise UdfCompileError(f"global {name!r} unsupported")

    def _load_attr(self, ins, stack):
        target = stack.pop()
        name = ins.argval
        is_method = ins.argrepr.startswith("NULL|self")
        if isinstance(target, _Module):
            if name not in _MATH_FNS:
                raise UdfCompileError(f"math.{name} unsupported")
            if is_method:
                stack.append(("mathfn", name))
                stack.append(_SELF)
            else:
                stack.append(("mathfn", name))
            return
        if isinstance(target, str):
            target = Literal(target)
        if isinstance(target, Expression):
            if is_method:
                stack.append(_BoundMethod(target, name))
                stack.append(_SELF)
            else:
                stack.append(_BoundMethod(target, name))
            return
        raise UdfCompileError(f"attribute {name!r} on {target!r}")

    def _call(self, nargs: int, stack):
        args = [stack.pop() for _ in range(nargs)][::-1]
        b = stack.pop()  # self_or_null (or folded-self marker)
        a = stack.pop()  # callable (or NULL from LOAD_GLOBAL order)
        if a is _NULL:
            callee = b
        elif b is _SELF:
            callee = a
        else:
            callee = a
            args = [b] + args  # b was a real self for an unbound call
        if isinstance(callee, _BoundMethod):
            stack.append(self._method(callee, args))
            return
        if isinstance(callee, tuple) and callee[0] == "mathfn":
            stack.append(self._mathfn(callee[1], args))
            return
        if isinstance(callee, tuple) and callee[0] == "builtin":
            stack.append(self._builtin(callee[1], args))
            return
        raise UdfCompileError(f"call of {callee!r} unsupported")

    _STR_METHODS0 = {"upper": Upper, "lower": Lower, "strip": StringTrim,
                     "lstrip": StringTrimLeft, "rstrip": StringTrimRight}

    def _method(self, m: _BoundMethod, args) -> Expression:
        if m.name in self._STR_METHODS0 and not args:
            return self._STR_METHODS0[m.name](m.target)
        if m.name == "startswith" and len(args) == 1:
            return StartsWith(m.target, _const_str(args[0]))
        if m.name == "endswith" and len(args) == 1:
            return EndsWith(m.target, _const_str(args[0]))
        if m.name == "replace" and len(args) == 2:
            return StringReplace(m.target, _const_str(args[0]),
                                 _const_str(args[1]))
        raise UdfCompileError(f"method .{m.name}() unsupported")

    def _mathfn(self, name: str, args) -> Expression:
        import spark_rapids_tpu.expr as E

        cls = getattr(E, _MATH_FNS[name])
        return cls(*[Cast(_lift(a), double) for a in args])

    def _builtin(self, name: str, args) -> Expression:
        if name == "abs" and len(args) == 1:
            return Abs(_lift(args[0]))
        if name == "len" and len(args) == 1:
            return Length(_lift(args[0]))
        if name == "min" and len(args) >= 2:
            return Least(*[_lift(a) for a in args])
        if name == "max" and len(args) >= 2:
            return Greatest(*[_lift(a) for a in args])
        if name == "round" and 1 <= len(args) <= 2:
            scale = 0
            if len(args) == 2:
                if isinstance(args[1], Expression):
                    raise UdfCompileError("round scale must be constant")
                scale = int(args[1])
            # Python round is banker's rounding = Spark bround
            return BRound(_lift(args[0]), scale)
        if name == "float" and len(args) == 1:
            return Cast(_lift(args[0]), double)
        if name == "int" and len(args) == 1:
            return Cast(_lift(args[0]), long)
        if name == "str" and len(args) == 1:
            return Cast(_lift(args[0]), string)
        if name == "bool" and len(args) == 1:
            return Cast(_lift(args[0]), boolean)
        raise UdfCompileError(f"builtin {name}({len(args)}) unsupported")


def compile_udf(fn, args: List[Expression]) -> Expression:
    """Compile a Python function's bytecode applied to engine
    expressions; raises UdfCompileError outside the supported subset."""
    try:
        return _Compiler(fn, args).compile()
    except UdfCompileError:
        raise
    except Exception as e:  # defensive: compiler bugs become fallbacks
        raise UdfCompileError(f"compiler error: {e!r}") from e
