"""PythonUDF — rowwise host fallback for uncompilable UDFs.

The reference runs uncompiled UDFs as black-box JVM calls on the CPU
plan; here the fallback expression has no device implementation, so the
planner tags its operator to the CPU backend (typesig's generic
no-device-impl rule) and cpu_eval applies the function rowwise.
"""

from __future__ import annotations

from typing import Optional

from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import DataType


class PythonUDF(Expression):
    def __init__(self, fn, children, return_type: DataType,
                 name: Optional[str] = None,
                 compile_error: Optional[str] = None):
        super().__init__(list(children))
        self.fn = fn
        self._return_type = return_type
        self.udf_name = name or getattr(fn, "__name__", "udf")
        self.compile_error = compile_error

    @property
    def dtype(self):
        return self._return_type

    @property
    def nullable(self):
        return True

    def key(self):
        return ("pyudf", id(self.fn),
                tuple(c.key() for c in self.children))

    def __repr__(self):
        return f"PythonUDF({self.udf_name})"
