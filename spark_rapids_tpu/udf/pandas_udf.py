"""Pandas/Arrow UDF exchange — the GpuArrowEvalPythonExec analog.

Reference (`execution/python/GpuArrowEvalPythonExec.scala` + 13 files,
`python/PythonWorkerSemaphore.scala`, SURVEY.md 2.8): device batches are
serialized to Arrow IPC, shipped to Python worker processes that run the
user's pandas function over pandas Series, and the results stream back
as Arrow; a semaphore caps concurrent workers.

Here the engine itself is Python, so the exchange's purpose is true
parallelism + isolation: each chunk ships as Arrow IPC bytes to a
process-pool worker (cloudpickle'd function, GIL-free), results return
as Arrow IPC. The pool size is the worker-semaphore analog
(spark.rapids.python.concurrentPythonWorkers role).

scalar pandas_udf only in v1 (Series... -> Series); grouped-map /
grouped-agg variants are follow-ups.
"""

from __future__ import annotations

import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, List, Optional

import pyarrow as pa

from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import DataType


class PandasWorkerError(RuntimeError):
    pass


class _WorkerProc:
    """One `python srtpu_pandas_worker.py serve` subprocess speaking
    length-prefixed pickle over its pipes."""

    def __init__(self):
        import os
        import subprocess
        import sys

        import srtpu_pandas_worker as w

        env = dict(os.environ)
        # workers never touch a device; keep jax inert if anything in
        # their (pyarrow/pandas-only) imports ever pulls it in
        env.setdefault("JAX_PLATFORMS", "cpu")
        self.proc = subprocess.Popen(
            [sys.executable, os.path.abspath(w.__file__), "serve"],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)

    def call(self, name: str, args: tuple):
        import pickle

        from srtpu_pandas_worker import _read_frame, _write_frame

        _write_frame(self.proc.stdin, pickle.dumps((name, args)))
        frame = _read_frame(self.proc.stdout)
        if frame is None:
            raise PandasWorkerError(
                f"pandas worker died (exit {self.proc.poll()})")
        status, payload = pickle.loads(frame)
        if status != "ok":
            raise PandasWorkerError(
                f"pandas UDF worker failed:\n{payload}")
        return payload

    def close(self):
        try:
            self.proc.stdin.close()
            self.proc.terminate()
            self.proc.wait(timeout=5)  # reap
        except Exception:  # incl. TimeoutExpired: best-effort teardown
            pass


class SubprocessPool:
    """ProcessPoolExecutor-shaped facade over the worker daemons (the
    reference's python daemon/worker pool, python/rapids/daemon.py +
    PythonWorkerSemaphore): one dispatcher thread per worker, tasks
    queue through a shared executor."""

    _DISPATCH_HEADROOM = 64

    def __init__(self, num_workers: int):
        import queue

        # dispatcher threads are cheap and idle-block on the worker
        # queue; size the executor with headroom so grow() never needs
        # to resize executor internals (concurrency is bounded by the
        # number of _WorkerProc entries in the queue)
        self._dispatch_cap = max(num_workers * 2,
                                 self._DISPATCH_HEADROOM)
        self._threads = ThreadPoolExecutor(
            max_workers=self._dispatch_cap,
            thread_name_prefix="srtpu-pandas-dispatch")
        self._total_workers = num_workers
        self._workers = queue.SimpleQueue()
        for _ in range(num_workers):
            self._workers.put(_WorkerProc())

    def grow(self, extra: int):
        import warnings

        for _ in range(extra):
            self._workers.put(_WorkerProc())
        self._total_workers += extra
        total = self._total_workers  # qsize() misses checked-out workers
        if total > self._dispatch_cap:
            warnings.warn(
                f"pandas worker pool grew to {total} workers but only "
                f"{self._dispatch_cap} dispatcher threads exist; "
                "concurrency is capped — create the session with the "
                "larger worker count instead")

    def submit(self, fn, *args):
        name = fn.__name__

        def run():
            w = self._workers.get()
            try:
                out = w.call(name, args)
            except BaseException:
                # ANY failure retires the worker (a BrokenPipeError
                # would otherwise leak it and starve the pool)
                w.close()
                self._workers.put(_WorkerProc())
                raise
            self._workers.put(w)
            return out

        return self._threads.submit(run)

    def shutdown(self, wait=True):
        self._threads.shutdown(wait=wait)
        try:
            while True:
                self._workers.get_nowait().close()
        except Exception:
            pass


_pool: Optional[SubprocessPool] = None
_pool_workers = 0
_pool_lock = threading.Lock()


def get_worker_pool(num_workers: int = 4) -> SubprocessPool:
    """Grow-only: resizing up adds workers; shrinking keeps the larger
    pool (rebuilding under in-flight dispatches would strand busy
    workers in an abandoned queue)."""
    global _pool, _pool_workers
    with _pool_lock:
        if _pool is None:
            _pool = SubprocessPool(num_workers)
            _pool_workers = num_workers
        elif num_workers > _pool_workers:
            _pool.grow(num_workers - _pool_workers)
            _pool_workers = num_workers
        return _pool


# The worker entry lives in the dependency-free top-level module
# srtpu_pandas_worker so worker processes never import this package
# (package import initializes the JAX backend).
from srtpu_pandas_worker import (  # noqa: E402
    ipc_bytes as _ipc_bytes,
    ipc_table as _ipc_table,
    worker_apply as _worker_apply,
)


class PandasUDF(Expression):
    """Scalar pandas UDF expression: evaluated on the host via the Arrow
    worker-process exchange; the planner's type checks route the
    enclosing operator to the CPU path (GpuArrowEvalPythonExec is a
    host-side exec in the reference too — only the batch transport
    touches the device)."""

    def __init__(self, fn: Callable, return_type: DataType,
                 children: List[Expression]):
        super().__init__(children)
        self.fn = fn
        self._dtype = return_type

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return True

    def key(self):
        return ("pandas_udf", id(self.fn),
                tuple(c.key() for c in self.children))

    def __repr__(self):
        return (f"pandas_udf({getattr(self.fn, '__name__', 'fn')}, "
                f"{self._dtype.simpleString})")


def eval_pandas_udf(e: PandasUDF, table: pa.Table,
                    chunk_rows: int = 65536,
                    num_workers: int = 4) -> pa.ChunkedArray:
    """Host evaluation: chunk the input, ship chunks to the worker pool
    concurrently, reassemble in order."""
    import cloudpickle

    from spark_rapids_tpu.exec import cpu_eval
    from spark_rapids_tpu.sqltypes.datatypes import to_arrow_type

    cols = {f"c{i}": cpu_eval.eval_expr(c, table)
            for i, c in enumerate(e.children)}
    work = pa.table(cols)
    out_type = to_arrow_type(e.dtype)
    type_blob = pa.schema([pa.field("r", out_type)]).serialize() \
        .to_pybytes()
    fn_bytes = pickle_fn(e.fn)
    pool = get_worker_pool(num_workers)
    futures = []
    for off in range(0, max(work.num_rows, 1), chunk_rows):
        piece = work.slice(off, min(chunk_rows, work.num_rows - off))
        if piece.num_rows == 0 and work.num_rows > 0:
            break
        futures.append(pool.submit(_worker_apply, fn_bytes,
                                   _ipc_bytes(piece), type_blob))
    chunks = [_ipc_table(f.result()).column("r") for f in futures]
    if not chunks:
        return pa.chunked_array([pa.array([], type=out_type)])
    return pa.chunked_array(
        [c for ch in chunks for c in ch.chunks])


def pickle_fn(fn) -> bytes:
    """Pickle a user function BY VALUE (workers must not import the
    user's module — it would transitively initialize jax)."""
    import inspect

    import cloudpickle

    mod = inspect.getmodule(fn)
    registered = False
    if mod is not None and getattr(mod, "__name__", "__main__") not in (
            "builtins",):
        try:
            cloudpickle.register_pickle_by_value(mod)
            registered = True
        except Exception:
            pass
    try:
        return cloudpickle.dumps(fn)
    finally:
        if registered:
            cloudpickle.unregister_pickle_by_value(mod)


def _schema_blob(schema: pa.Schema) -> bytes:
    return schema.serialize().to_pybytes()


def _group_slices(table: pa.Table, key_names):
    """Contiguous per-group slices (sorted by keys, null keys grouped)."""
    import pyarrow.compute as pc

    if table.num_rows == 0:
        return
    sort_keys = [(k, "ascending") for k in key_names]
    idx = pc.sort_indices(table, sort_keys=sort_keys,
                          null_placement="at_end")
    s = table.take(idx)
    import numpy as np

    keys = [s.column(k) for k in key_names]
    n = s.num_rows
    boundary = np.zeros(n, dtype=bool)
    boundary[0] = True
    for k in keys:
        vals = k.to_pandas()
        neq = vals.ne(vals.shift()) & ~(vals.isna() & vals.isna().shift(
            fill_value=False))
        boundary |= neq.to_numpy(dtype=bool, na_value=True)
    starts = np.flatnonzero(boundary)
    ends = np.append(starts[1:], n)
    for a, b in zip(starts, ends):
        yield s.slice(a, b - a)


def apply_in_pandas_grouped(fn, key_names, table: pa.Table,
                            out_schema: pa.Schema,
                            num_workers: int = 4) -> pa.Table:
    """groupBy(...).applyInPandas driver side: each key group ships to
    the worker pool as one Arrow chunk (GpuArrowEvalPythonExec grouped-
    map role)."""
    from srtpu_pandas_worker import worker_apply_df

    fn_bytes = pickle_fn(fn)
    blob = _schema_blob(out_schema)
    pool = get_worker_pool(num_workers)
    futures = [pool.submit(worker_apply_df, fn_bytes, _ipc_bytes(g),
                           blob)
               for g in _group_slices(table, key_names)]
    parts = [_ipc_table(f.result()) for f in futures]
    if not parts:
        return out_schema.empty_table()
    return pa.concat_tables(parts, promote_options="none")


def map_in_pandas(fn, table: pa.Table, out_schema: pa.Schema,
                  chunk_rows: int = 65536,
                  num_workers: int = 4) -> pa.Table:
    """df.mapInPandas driver side. Spark contract: the function runs
    ONCE per partition over an iterator of batches (state may carry
    across the iterator), so the whole partition ships to one worker,
    which feeds the function chunk-sized frames."""
    from srtpu_pandas_worker import worker_apply_df

    names = out_schema.names

    def once(df):
        import pandas as pd

        # re-chunk inside the worker so fn sees the iterator contract
        chunks = [df.iloc[i:i + chunk_rows]
                  for i in range(0, max(len(df), 1), chunk_rows)]
        outs = [o for o in fn(iter(chunks)) if len(o)]
        if not outs:
            return pd.DataFrame({c: [] for c in names})
        return pd.concat(outs, ignore_index=True)

    fn_bytes = pickle_fn(once)
    blob = _schema_blob(out_schema)
    pool = get_worker_pool(num_workers)
    fut = pool.submit(worker_apply_df, fn_bytes, _ipc_bytes(table),
                      blob)
    return _ipc_table(fut.result())


def apply_in_pandas_cogrouped(fn, key_names, left: pa.Table,
                              right: pa.Table, out_schema: pa.Schema,
                              num_workers: int = 4) -> pa.Table:
    """cogroup(...).applyInPandas driver side: align per-key groups
    from both sides (missing side = empty frame, Spark semantics)."""
    from srtpu_pandas_worker import worker_apply_cogroup

    def key_of(g):
        return tuple(g.column(k)[0].as_py() for k in key_names)

    lmap = {key_of(g): g for g in _group_slices(left, key_names)}
    rmap = {key_of(g): g for g in _group_slices(right, key_names)}
    fn_bytes = pickle_fn(fn)
    blob = _schema_blob(out_schema)
    pool = get_worker_pool(num_workers)
    futures = []
    for k in sorted(set(lmap) | set(rmap),
                    key=lambda t: tuple((v is None, v) for v in t)):
        lg = lmap.get(k, left.schema.empty_table())
        rg = rmap.get(k, right.schema.empty_table())
        futures.append(pool.submit(worker_apply_cogroup, fn_bytes,
                                   _ipc_bytes(lg), _ipc_bytes(rg),
                                   blob))
    parts = [_ipc_table(f.result()) for f in futures]
    if not parts:
        return out_schema.empty_table()
    return pa.concat_tables(parts, promote_options="none")
