"""Pandas/Arrow UDF exchange — the GpuArrowEvalPythonExec analog.

Reference (`execution/python/GpuArrowEvalPythonExec.scala` + 13 files,
`python/PythonWorkerSemaphore.scala`, SURVEY.md 2.8): device batches are
serialized to Arrow IPC, shipped to Python worker processes that run the
user's pandas function over pandas Series, and the results stream back
as Arrow; a semaphore caps concurrent workers.

Here the engine itself is Python, so the exchange's purpose is true
parallelism + isolation: each chunk ships as Arrow IPC bytes to a
process-pool worker (cloudpickle'd function, GIL-free), results return
as Arrow IPC. The pool size is the worker-semaphore analog
(spark.rapids.python.concurrentPythonWorkers role).

scalar pandas_udf only in v1 (Series... -> Series); grouped-map /
grouped-agg variants are follow-ups.
"""

from __future__ import annotations

import threading
from concurrent.futures import ProcessPoolExecutor
from typing import Callable, List, Optional

import pyarrow as pa

from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import DataType

_pool: Optional[ProcessPoolExecutor] = None
_pool_workers = 0
_pool_lock = threading.Lock()


def get_worker_pool(num_workers: int = 4) -> ProcessPoolExecutor:
    global _pool, _pool_workers
    import multiprocessing

    with _pool_lock:
        if _pool is None or _pool_workers != num_workers:
            if _pool is not None:
                _pool.shutdown(wait=False)
            # forkserver, not fork: the parent runs JAX's thread pools
            # and a direct fork can deadlock on their held locks; the
            # forkserver is exec'd fresh and forks clean children (and
            # unlike spawn it does not re-run __main__)
            _pool = ProcessPoolExecutor(
                max_workers=num_workers,
                mp_context=multiprocessing.get_context("forkserver"))
            _pool_workers = num_workers
        return _pool


# The worker entry lives in the dependency-free top-level module
# srtpu_pandas_worker so worker processes never import this package
# (package import initializes the JAX backend).
from srtpu_pandas_worker import (  # noqa: E402
    ipc_bytes as _ipc_bytes,
    ipc_table as _ipc_table,
    worker_apply as _worker_apply,
)


class PandasUDF(Expression):
    """Scalar pandas UDF expression: evaluated on the host via the Arrow
    worker-process exchange; the planner's type checks route the
    enclosing operator to the CPU path (GpuArrowEvalPythonExec is a
    host-side exec in the reference too — only the batch transport
    touches the device)."""

    def __init__(self, fn: Callable, return_type: DataType,
                 children: List[Expression]):
        super().__init__(children)
        self.fn = fn
        self._dtype = return_type

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return True

    def key(self):
        return ("pandas_udf", id(self.fn),
                tuple(c.key() for c in self.children))

    def __repr__(self):
        return (f"pandas_udf({getattr(self.fn, '__name__', 'fn')}, "
                f"{self._dtype.simpleString})")


def eval_pandas_udf(e: PandasUDF, table: pa.Table,
                    chunk_rows: int = 65536,
                    num_workers: int = 4) -> pa.ChunkedArray:
    """Host evaluation: chunk the input, ship chunks to the worker pool
    concurrently, reassemble in order."""
    import cloudpickle

    from spark_rapids_tpu.exec import cpu_eval
    from spark_rapids_tpu.sqltypes.datatypes import to_arrow_type

    cols = {f"c{i}": cpu_eval.eval_expr(c, table)
            for i, c in enumerate(e.children)}
    work = pa.table(cols)
    out_type = to_arrow_type(e.dtype)
    type_blob = pa.schema([pa.field("r", out_type)]).serialize() \
        .to_pybytes()
    # pickle the UDF by value: a by-reference pickle would make workers
    # import the user's module (and transitively this package, whose
    # import initializes the JAX backend)
    import inspect

    mod = inspect.getmodule(e.fn)
    registered = False
    if mod is not None and getattr(mod, "__name__", "__main__") not in (
            "builtins",):
        try:
            cloudpickle.register_pickle_by_value(mod)
            registered = True
        except Exception:
            pass
    try:
        fn_bytes = cloudpickle.dumps(e.fn)
    finally:
        if registered:
            cloudpickle.unregister_pickle_by_value(mod)
    pool = get_worker_pool(num_workers)
    futures = []
    for off in range(0, max(work.num_rows, 1), chunk_rows):
        piece = work.slice(off, min(chunk_rows, work.num_rows - off))
        if piece.num_rows == 0 and work.num_rows > 0:
            break
        futures.append(pool.submit(_worker_apply, fn_bytes,
                                   _ipc_bytes(piece), type_blob))
    chunks = [_ipc_table(f.result()).column("r") for f in futures]
    if not chunks:
        return pa.chunked_array([pa.array([], type=out_type)])
    return pa.chunked_array(
        [c for ch in chunks for c in ch.chunks])
