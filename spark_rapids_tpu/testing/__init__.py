from spark_rapids_tpu.testing.asserts import (  # noqa: F401
    assert_tpu_and_cpu_are_equal_collect,
    assert_tpu_fallback_collect,
    with_cpu_session,
    with_tpu_session,
)
