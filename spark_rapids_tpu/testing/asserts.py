"""Differential test harness — the integration-test core of the
reference, re-provided as a library.

Reference pattern (`integration_tests/src/main/python/asserts.py:475-579`):
run the same dataframe function under a CPU session and a device session
and diff collected results; `assert_gpu_fallback_collect` additionally
asserts that a given operator did NOT run on device. Same surface here:

    assert_tpu_and_cpu_are_equal_collect(
        lambda spark: spark.read.parquet(p).groupBy("a").sum("b"))

The CPU session is this engine with every operator forced to the pyarrow
backend (spark.rapids.tpu.test.cpuOracle=true), the moral equivalent of
running vanilla CPU Spark.
"""

from __future__ import annotations

import math
from typing import Callable, Dict, Optional

import pyarrow as pa

from spark_rapids_tpu.api.session import TpuSparkSession


def with_tpu_session(fn, conf: Optional[Dict] = None):
    settings = dict(conf or {})
    spark = TpuSparkSession(settings)
    try:
        return fn(spark)
    finally:
        spark.stop()


def with_cpu_session(fn, conf: Optional[Dict] = None):
    settings = dict(conf or {})
    settings["spark.rapids.tpu.test.cpuOracle"] = True
    spark = TpuSparkSession(settings)
    try:
        return fn(spark)
    finally:
        spark.stop()


def _sort_table(t: pa.Table) -> pa.Table:
    import pyarrow.compute as pc

    if t.num_rows <= 1 or t.num_columns == 0:
        return t
    # duplicate output names are legal (join keeps both sides' columns);
    # sort through a uniquely-renamed view. Nested columns are not
    # sortable in arrow: key on the sortable subset only.
    uniq = [f"c{i}" for i in range(t.num_columns)]
    view = t.rename_columns(uniq)
    # (name, order) pairs; null placement is a SortOptions-level knob
    # in arrow, not a per-key one
    keys = [(n, "ascending") for n, f in zip(uniq, t.schema)
            if not pa.types.is_nested(f.type)]
    if not keys:
        return t
    try:
        return t.take(pc.sort_indices(view, sort_keys=keys,
                                      null_placement="at_start"))
    except (pa.ArrowNotImplementedError, pa.ArrowTypeError):
        return t


def _values_equal(a, b, rel_tol: float) -> bool:
    if a is None or b is None:
        return a is None and b is None
    if isinstance(a, float) or isinstance(b, float):
        fa, fb = float(a), float(b)
        if math.isnan(fa) or math.isnan(fb):
            return math.isnan(fa) and math.isnan(fb)
        return math.isclose(fa, fb, rel_tol=rel_tol, abs_tol=1e-11)
    return a == b


def assert_tables_equal(tpu: pa.Table, cpu: pa.Table,
                        ignore_order: bool = True,
                        rel_tol: float = 1e-9):
    assert tpu.column_names == cpu.column_names, \
        f"column mismatch: {tpu.column_names} vs {cpu.column_names}"
    assert tpu.num_rows == cpu.num_rows, \
        f"row count mismatch: tpu={tpu.num_rows} cpu={cpu.num_rows}"
    if ignore_order:
        tpu, cpu = _sort_table(tpu), _sort_table(cpu)
    for ci, name in enumerate(tpu.column_names):
        av = tpu.column(ci).to_pylist()
        bv = cpu.column(ci).to_pylist()
        for i, (x, y) in enumerate(zip(av, bv)):
            assert _values_equal(x, y, rel_tol), (
                f"column {name!r} row {i}: tpu={x!r} cpu={y!r}")


def assert_tpu_and_cpu_are_equal_collect(
        df_fn: Callable, conf: Optional[Dict] = None,
        ignore_order: bool = True, rel_tol: float = 1e-9):
    """Run df_fn under both backends and diff the collected tables."""
    tpu = with_tpu_session(lambda s: df_fn(s).collect_arrow(), conf)
    cpu = with_cpu_session(lambda s: df_fn(s).collect_arrow(), conf)
    assert_tables_equal(tpu, cpu, ignore_order=ignore_order,
                        rel_tol=rel_tol)
    return tpu


def assert_tpu_fallback_collect(df_fn: Callable, fallback_class: str,
                                conf: Optional[Dict] = None):
    """Assert the plan places `fallback_class` on CPU yet results still
    match (assert_gpu_fallback_collect analog, asserts.py:439)."""
    captured = {}

    def run(spark):
        df = df_fn(spark)
        phys, meta = df._physical()
        captured["phys"] = phys
        return phys.collect()

    tpu = with_tpu_session(run, conf)

    def walk(p):
        yield p
        for c in p.children:
            yield from walk(c)

    names = [type(p).__name__ for p in walk(captured["phys"])]
    assert any(n == fallback_class for n in names), (
        f"expected {fallback_class} in physical plan, got {names}")
    cpu = with_cpu_session(lambda s: df_fn(s).collect_arrow(), conf)
    assert_tables_equal(tpu, cpu)
    return tpu
