"""Scale-test harness — the ScaleTest module analog (reference
integration_tests/ScaleTest.md + tests/scaletest/: a deterministic
join/agg/window-heavy query set q1..q10 over generated tables, used for
perf regression and memory-pressure coverage at configurable scale).

Data model (scaled by `scale_factor`; seeded, reproducible):
- fact   : wide fact table with skewed join key (SkewedKeyGen)
- dim    : small dimension keyed 0..card-1 (broadcast-size)
- events : timestamped rows for window/sort queries

Run programmatically (`run_scale_test`) or as a CLI:
    python -m spark_rapids_tpu.testing.scaletest --scale 1 --queries q1,q5
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, List, Optional

import pyarrow.parquet as pq

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.testing.datagen import (
    ArrayGen,
    CorrelatedGen,
    DateGen,
    DoubleGen,
    IntGen,
    LongGen,
    RepeatSeqGen,
    SkewedKeyGen,
    StringGen,
    gen_table,
)

BASE_ROWS = 100_000
DIM_CARD = 1_000


def generate_data(out_dir: str, scale_factor: float = 1.0,
                  seed: int = 42, files_per_table: int = 4) -> Dict[str,
                                                                    str]:
    """Write the test tables as multi-file parquet; returns table paths."""
    n_fact = max(1000, int(BASE_ROWS * scale_factor))
    n_events = max(1000, int(BASE_ROWS * scale_factor // 2))
    fact = gen_table([
        ("k", SkewedKeyGen(IntGen(0, DIM_CARD - 1, nullable=False),
                           DIM_CARD, skew=1.2, nullable=False)),
        ("amount", DoubleGen(include_specials=False)),
        ("qty", LongGen(lo=1, hi=100, nullable=False)),
        ("rebate", CorrelatedGen(
            "amount", lambda a, rng: a * 0.1 + rng.random(len(a)))),
        ("tags", ArrayGen(IntGen(0, 50, nullable=False), max_len=4)),
        ("day", DateGen()),
    ], n=n_fact, seed=seed)
    dim = gen_table([
        ("k", RepeatSeqGen(IntGen(0, DIM_CARD - 1, nullable=False),
                           DIM_CARD, nullable=False)),
        ("region", IntGen(0, 25, nullable=False)),
        ("name", StringGen(max_len=10, cardinality=200)),
    ], n=DIM_CARD, seed=seed + 1)
    events = gen_table([
        ("user", RepeatSeqGen(IntGen(0, 500, nullable=False), 500,
                              nullable=False)),
        ("ts", LongGen(lo=0, hi=10_000_000, nullable=False)),
        ("value", DoubleGen(include_specials=False)),
    ], n=n_events, seed=seed + 2)
    paths = {}
    for name, t in (("fact", fact), ("dim", dim), ("events", events)):
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        per = max(1, t.num_rows // files_per_table)
        for i in range(0, t.num_rows, per):
            pq.write_table(t.slice(i, per),
                           os.path.join(d, f"part-{i // per:04d}.parquet"))
        paths[name] = d
    return paths


# ------------------------------------------------------------ query set

def _q1(s, p):
    """group-by agg over the skewed key."""
    return (s.read.parquet(p["fact"]).groupBy("k")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n"), F.avg("qty").alias("aq")))


def _q2(s, p):
    """global aggregate."""
    return s.read.parquet(p["fact"]).agg(
        F.sum("amount").alias("t"), F.count("*").alias("n"))


def _q3(s, p):
    """filter + projection arithmetic + agg."""
    return (s.read.parquet(p["fact"])
            .filter(F.col("amount") > 10.0)
            .select("k", (F.col("amount") * F.col("qty")).alias("rev"))
            .groupBy("k").agg(F.sum("rev").alias("total")))


def _q4(s, p):
    """broadcast join + agg."""
    fact = s.read.parquet(p["fact"])
    dim = s.read.parquet(p["dim"])
    return (fact.join(dim, on="k", how="inner")
            .groupBy("region").agg(F.sum("amount").alias("rev")))


def _q5(s, p):
    """shuffled join + agg + sort (the NDS-q5-shaped slice)."""
    fact = s.read.parquet(p["fact"])
    dim = s.read.parquet(p["dim"])
    return (fact.filter(F.col("amount") > 5.0)
            .join(dim, on="k", how="inner")
            .groupBy("region")
            .agg(F.sum("amount").alias("rev"),
                 F.count("*").alias("n"))
            .orderBy(F.col("rev").desc()))


def _q6(s, p):
    """window ranking over partitions."""
    from spark_rapids_tpu.api.window import Window

    ev = s.read.parquet(p["events"])
    w = Window.partitionBy("user").orderBy("ts")
    return ev.select("user", "ts",
                     F.row_number().over(w).alias("rn"))


def _q7(s, p):
    """global sort + limit (TopN)."""
    return (s.read.parquet(p["fact"])
            .orderBy(F.col("amount").desc()).limit(100))


def _q8(s, p):
    """explode nested arrays + agg."""
    return (s.read.parquet(p["fact"])
            .select("k", F.explode(F.col("tags")).alias("tag"))
            .groupBy("tag").agg(F.count("*").alias("n")))


def _q9(s, p):
    """left anti join (dim keys never sold)."""
    fact = s.read.parquet(p["fact"])
    dim = s.read.parquet(p["dim"])
    return dim.join(fact, on="k", how="left_anti").select("k", "region")


def _q10(s, p):
    """distinct + order (dedup pipeline)."""
    return (s.read.parquet(p["fact"]).select("k", "qty")
            .distinct().orderBy("k", "qty"))


QUERIES: Dict[str, Callable] = {
    "q1": _q1, "q2": _q2, "q3": _q3, "q4": _q4, "q5": _q5,
    "q6": _q6, "q7": _q7, "q8": _q8, "q9": _q9, "q10": _q10,
}


def run_scale_test(spark, paths: Dict[str, str],
                   queries: Optional[List[str]] = None,
                   iterations: int = 1) -> Dict[str, dict]:
    """Run the query set; returns {query: {elapsed_s, rows}}."""
    results = {}
    for name in (queries or sorted(QUERIES)):
        fn = QUERIES[name]
        best = None
        rows = 0
        for _ in range(iterations):
            t0 = time.perf_counter()
            out = fn(spark, paths).collect_arrow()
            dt = time.perf_counter() - t0
            rows = out.num_rows
            best = dt if best is None else min(best, dt)
        results[name] = {"elapsed_s": round(best, 4), "rows": rows}
    return results


def main():
    import argparse
    import json
    import tempfile

    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", type=float, default=1.0)
    ap.add_argument("--queries", type=str, default="")
    ap.add_argument("--data-dir", type=str, default="")
    ap.add_argument("--iterations", type=int, default=1)
    args = ap.parse_args()
    from spark_rapids_tpu.api.session import TpuSparkSession

    out_dir = args.data_dir or tempfile.mkdtemp(prefix="srtpu-scale-")
    paths = generate_data(out_dir, args.scale)
    spark = TpuSparkSession({})
    queries = [q for q in args.queries.split(",") if q] or None
    print(json.dumps(run_scale_test(spark, paths, queries,
                                    args.iterations), indent=2))


if __name__ == "__main__":
    main()
