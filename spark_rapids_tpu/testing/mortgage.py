"""Mortgage ETL workload — the MortgageSpark.scala benchmark analog
(reference integration_tests/.../mortgage/MortgageSpark.scala +
mortgage_test.py): the classic two-table pipeline — performance records
joined with acquisitions, per-loan delinquency aggregation, feature
assembly — used as a perf/regression workload and as the zero-copy ML
handoff source (ColumnarRdd -> XGBoost in the reference;
api/columnar_rdd.py here)."""

from __future__ import annotations

import os
from typing import Dict

import numpy as np
import pyarrow as pa
import pyarrow.parquet as pq

from spark_rapids_tpu.api import functions as F

SELLERS = 30
BASE_LOANS = 10_000
MONTHS = 24


def generate_mortgage_data(out_dir: str, scale_factor: float = 1.0,
                           seed: int = 7, files_per_table: int = 4
                           ) -> Dict[str, str]:
    rng = np.random.default_rng(seed)
    n_loans = max(200, int(BASE_LOANS * scale_factor))
    loan_ids = np.arange(n_loans, dtype=np.int64)
    acq = pa.table({
        "loan_id": pa.array(loan_ids),
        "seller": pa.array(rng.integers(0, SELLERS, n_loans),
                           type=pa.int64()),
        "orig_rate": pa.array(2.5 + rng.random(n_loans) * 5,
                              type=pa.float64()),
        "orig_upb": pa.array(rng.integers(50_000, 800_000, n_loans)
                             .astype(np.float64)),
        "dti": pa.array(rng.random(n_loans) * 60, type=pa.float64()),
        "credit_score": pa.array(rng.integers(450, 850, n_loans),
                                 type=pa.int64()),
    })
    n_perf = n_loans * MONTHS
    perf_loans = np.repeat(loan_ids, MONTHS)
    months = np.tile(np.arange(MONTHS, dtype=np.int64), n_loans)
    delinq = rng.choice([0, 0, 0, 0, 0, 1, 2, 3],
                        size=n_perf).astype(np.int64)
    perf = pa.table({
        "loan_id": pa.array(perf_loans),
        "month": pa.array(months),
        "current_upb": pa.array(
            rng.random(n_perf) * 800_000, type=pa.float64()),
        "delinq_status": pa.array(delinq),
        "interest_paid": pa.array(rng.random(n_perf) * 4000,
                                  type=pa.float64()),
    })
    paths = {}
    for name, t in (("acq", acq), ("perf", perf)):
        d = os.path.join(out_dir, name)
        os.makedirs(d, exist_ok=True)
        per = max(1, t.num_rows // files_per_table)
        for i in range(0, t.num_rows, per):
            pq.write_table(t.slice(i, per),
                           os.path.join(d, f"part-{i // per:04d}.parquet"))
        paths[name] = d
    return paths


def mortgage_etl(spark, paths: Dict[str, str]):
    """The ETL: per-loan delinquency features joined onto acquisitions
    (the XGBoost feature frame of the reference pipeline)."""
    perf = spark.read.parquet(paths["perf"])
    acq = spark.read.parquet(paths["acq"])
    loan_features = (
        perf.groupBy("loan_id")
        .agg(F.max("delinq_status").alias("max_delinq"),
             F.sum("interest_paid").alias("total_interest"),
             F.avg("current_upb").alias("avg_upb"),
             F.count("*").alias("n_reports")))
    joined = acq.join(loan_features, on="loan_id", how="inner")
    return joined.select(
        "loan_id", "seller", "orig_rate", "dti", "credit_score",
        "max_delinq", "total_interest", "avg_upb",
        (F.col("avg_upb") / F.col("orig_upb")).alias("upb_ratio"),
        (F.col("max_delinq") >= 1).alias("ever_delinq"))


def mortgage_summary(spark, paths: Dict[str, str]):
    """Seller-level risk rollup (the reporting query of the suite)."""
    etl = mortgage_etl(spark, paths)
    return (etl.groupBy("seller")
            .agg(F.avg("orig_rate").alias("avg_rate"),
                 F.sum(F.col("ever_delinq").cast("long"))
                 .alias("delinq_loans"),
                 F.count("*").alias("loans"))
            .orderBy("seller"))
