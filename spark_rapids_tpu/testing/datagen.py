"""Seeded, composable data generators — the data_gen.py / datagen module
analog (reference `integration_tests/src/main/python/data_gen.py` and the
Scala `datagen/` module): deterministic generation with null ratios,
cardinality control, and special-value injection, producing pyarrow
tables.
"""

from __future__ import annotations

import string as _string
from typing import List, Optional, Tuple

import numpy as np
import pyarrow as pa


class DataGen:
    arrow_type: pa.DataType = None

    def __init__(self, nullable: bool = True, null_ratio: float = 0.1):
        self.nullable = nullable
        self.null_ratio = null_ratio if nullable else 0.0

    def generate(self, n: int, rng: np.random.Generator) -> pa.Array:
        vals = self._values(n, rng)
        if self.null_ratio > 0:
            mask = rng.random(n) < self.null_ratio
        else:
            mask = None
        return pa.array(vals, type=self.arrow_type, mask=mask)

    def _values(self, n, rng):
        raise NotImplementedError


class IntGen(DataGen):
    arrow_type = pa.int32()

    def __init__(self, lo=-(2**31), hi=2**31 - 1, **kw):
        super().__init__(**kw)
        self.lo, self.hi = lo, hi

    def _values(self, n, rng):
        base = rng.integers(self.lo, self.hi, size=n, dtype=np.int64,
                            endpoint=True).astype(np.int32)
        # inject boundary values like the reference's special cases
        for i, v in enumerate([0, self.lo, self.hi]):
            if n > i:
                base[i] = v
        return base


class LongGen(DataGen):
    arrow_type = pa.int64()

    def __init__(self, lo=-(2**63), hi=2**63 - 1, **kw):
        super().__init__(**kw)
        self.lo, self.hi = lo, hi

    def _values(self, n, rng):
        base = rng.integers(self.lo // 2, self.hi // 2, size=n,
                            dtype=np.int64)
        for i, v in enumerate([0, self.lo, self.hi]):
            if n > i:
                base[i] = v
        return base


class DoubleGen(DataGen):
    arrow_type = pa.float64()

    def __init__(self, include_specials: bool = True, **kw):
        super().__init__(**kw)
        self.include_specials = include_specials

    def _values(self, n, rng):
        base = (rng.random(n) - 0.5) * 1e6
        if self.include_specials:
            specials = [0.0, -0.0, np.inf, -np.inf, np.nan, 1e-300, -1e300]
            for i, v in enumerate(specials):
                if n > i + 3:
                    base[i + 3] = v
        return base


class FloatGen(DoubleGen):
    arrow_type = pa.float32()

    def _values(self, n, rng):
        return super()._values(n, rng).astype(np.float32)


class BooleanGen(DataGen):
    arrow_type = pa.bool_()

    def _values(self, n, rng):
        return rng.random(n) < 0.5


class StringGen(DataGen):
    arrow_type = pa.string()

    def __init__(self, max_len: int = 12, charset: str = None,
                 cardinality: Optional[int] = None, **kw):
        super().__init__(**kw)
        self.max_len = max_len
        self.charset = charset or (_string.ascii_letters + _string.digits)
        self.cardinality = cardinality

    def _values(self, n, rng):
        def one():
            ln = int(rng.integers(0, self.max_len + 1))
            return "".join(rng.choice(list(self.charset), size=ln))

        if self.cardinality:
            pool = [one() for _ in range(self.cardinality)]
            return [pool[int(rng.integers(0, len(pool)))]
                    for _ in range(n)]
        return [one() for _ in range(n)]


class DateGen(DataGen):
    arrow_type = pa.date32()

    def __init__(self, lo_days=-25567, hi_days=25567, **kw):  # 1900..2040
        super().__init__(**kw)
        self.lo, self.hi = lo_days, hi_days

    def _values(self, n, rng):
        return rng.integers(self.lo, self.hi, size=n).astype(np.int32)


class TimestampGen(DataGen):
    arrow_type = pa.timestamp("us", tz="UTC")

    def _values(self, n, rng):
        return rng.integers(-2_208_988_800_000_000, 2_524_608_000_000_000,
                            size=n)  # ~1900..2050


class DecimalGen(DataGen):
    def __init__(self, precision=9, scale=2, **kw):
        super().__init__(**kw)
        self.precision, self.scale = precision, scale
        self.arrow_type = pa.decimal128(precision, scale)

    def _values(self, n, rng):
        import decimal

        hi = 10 ** min(self.precision, 18) - 1
        ints = rng.integers(-hi, hi, size=n)
        return [decimal.Decimal(int(v)).scaleb(-self.scale) for v in ints]


class RepeatSeqGen(DataGen):
    """Low-cardinality key generator (group/join keys with controlled
    cardinality + skew — the datagen module's key feature)."""

    def __init__(self, child: DataGen, cardinality: int, **kw):
        super().__init__(nullable=child.nullable,
                         null_ratio=child.null_ratio)
        self.child = child
        self.cardinality = cardinality
        self.arrow_type = child.arrow_type

    def _values(self, n, rng):
        pool = self.child._values(self.cardinality, rng)
        idx = rng.integers(0, self.cardinality, size=n)
        if isinstance(pool, np.ndarray):
            return pool[idx]
        return [pool[i] for i in idx]


class SkewedKeyGen(DataGen):
    """Zipf-skewed key picks over a pool (the datagen module's skew
    control, reference datagen/README.md): a few hot keys dominate, the
    tail follows a power law — the shape that breaks naive partitioning."""

    def __init__(self, child: DataGen, cardinality: int,
                 skew: float = 1.5, **kw):
        super().__init__(nullable=child.nullable,
                         null_ratio=child.null_ratio)
        self.child = child
        self.cardinality = cardinality
        self.skew = skew
        self.arrow_type = child.arrow_type

    def _values(self, n, rng):
        pool = self.child._values(self.cardinality, rng)
        ranks = np.arange(1, self.cardinality + 1, dtype=np.float64)
        p = ranks ** (-self.skew)
        p /= p.sum()
        idx = rng.choice(self.cardinality, size=n, p=p)
        if isinstance(pool, np.ndarray):
            return pool[idx]
        return [pool[i] for i in idx]


class CorrelatedGen(DataGen):
    """Value derived from another generated column plus noise (the
    datagen module's correlation control): fn(other_values, rng) -> np
    array. Requires gen_table, which passes prior columns through."""

    arrow_type = pa.float64()

    def __init__(self, source: str, fn, **kw):
        super().__init__(**kw)
        self.source = source
        self.fn = fn

    def generate_with(self, n, rng, built: dict) -> pa.Array:
        src = built[self.source]
        src_np = np.asarray(src.to_pandas())
        vals = self.fn(src_np, rng)
        mask = (rng.random(n) < self.null_ratio) if self.null_ratio \
            else None
        return pa.array(np.asarray(vals, dtype=np.float64),
                        type=self.arrow_type, mask=mask)

    def _values(self, n, rng):
        raise RuntimeError("CorrelatedGen requires gen_table")


class ArrayGen(DataGen):
    """Lists of a primitive child generator (nested-type coverage)."""

    def __init__(self, child: DataGen, max_len: int = 5, **kw):
        super().__init__(**kw)
        self.child = child
        self.max_len = max_len
        self.arrow_type = pa.list_(child.arrow_type)

    def _values(self, n, rng):
        lens = rng.integers(0, self.max_len + 1, size=n)
        flat = self.child.generate(int(lens.sum()), rng)
        out = []
        off = 0
        flat_list = flat.to_pylist()
        for ln in lens:
            out.append(flat_list[off:off + int(ln)])
            off += int(ln)
        return out


def gen_table(gens: List[Tuple[str, DataGen]], n: int,
              seed: int = 0) -> pa.Table:
    rng = np.random.default_rng(seed)
    built = {}
    for name, g in gens:
        if isinstance(g, CorrelatedGen):
            built[name] = g.generate_with(n, rng, built)
        else:
            built[name] = g.generate(n, rng)
    return pa.table(built)


# Standard gen sets (reference data_gen.py naming)
numeric_gens = [IntGen(), LongGen(), DoubleGen()]
all_basic_gens = [BooleanGen(), IntGen(), LongGen(), FloatGen(),
                  DoubleGen(), StringGen(), DateGen(), TimestampGen()]
