"""Resilient stage scheduler — the DAGScheduler / TaskSetManager analog.

PR 2 hardened every *intra-process* failure domain (CRC'd blocks,
backoff, the degradation ladder); this layer recovers *task-shaped*
failures the way the reference plugin inherits them from Spark's
DAGScheduler (stage re-attempts, lost-map-output recomputation,
executor exclusion, speculation — TaskSetManager.scala /
DAGScheduler.scala roles):

- Each stage is a TaskSet of DETERMINISTIC, re-runnable task attempts.
  A `Task` carries its lineage (a partition index + a closure over the
  plan fragment that recomputes it from source), so any partition can
  be re-produced at any time.
- **Worker eviction**: an attempt that dies with `WorkerLost` (a real
  process crash in the process backend, heartbeat expiry, or an
  injected `worker.crash` fault) evicts its worker for the session and
  re-runs the in-flight partition on another worker, bounded by
  `spark.rapids.tpu.stage.maxAttempts`.
- **Speculation**: once `speculation.quantile` of the stage completed,
  tasks running longer than `speculation.multiplier` x the median get a
  duplicate attempt. Output is attempt-tagged (shuffle staging in
  shuffle/manager.py, the PendingBatches discipline generalized) and
  COMMIT-ONCE: the first attempt to finish commits, the loser's output
  is discarded — never double-counted, never leaked.
- **Lost-output recovery** rides the same Task machinery from the
  exchange side: `TpuShuffleExchangeExec.fetch_blocks` catches a
  `ShuffleFetchError` that survived the block-level retry budget and
  re-runs ONLY the upstream map task owning the missing blocks
  (`stats.recomputedPartitions`).

Two backends execute attempts: the in-process `ThreadBackend` (virtual
workers over a thread pool — the default for the single-process
engine), and `parallel/process_pool.ProcessBackend` (real OS worker
processes with heartbeat liveness, where `kill -9` is survivable).

Chaos sites `worker.crash` and `task.straggler` (runtime/faults.py)
inject at attempt launch so ci/chaos_check.sh proves result
equivalence under crash-retry and speculative duplication.
"""

from __future__ import annotations

import itertools
import math
import queue
import statistics
import threading
import time
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from spark_rapids_tpu.runtime.errors import WorkerLost
from spark_rapids_tpu.runtime.faults import InjectedFault

# --------------------------------------------------------------- stats

_FIELDS = ("tasksLaunched", "tasksRetried", "tasksSpeculated",
           "speculativeWins", "recomputedPartitions", "evictedWorkers",
           "stagesRun")


class _SchedulerStats:
    """Process-wide scheduler ledger (the compile_cache.stats pattern):
    per-query deltas land in last_execution['scheduler'], totals in
    session.robustness_metrics['scheduler']."""

    def __init__(self):
        self._lock = threading.Lock()
        self._v = {f: 0 for f in _FIELDS}

    def add(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._v[field] += n

    def snapshot(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._v)

    @staticmethod
    def delta(before: Dict[str, int], after: Dict[str, int]
              ) -> Dict[str, int]:
        return {k: after[k] - before.get(k, 0) for k in after}


stats = _SchedulerStats()

_stage_token = itertools.count(1)


def tree_consuming(plan) -> bool:
    """True when any node in a physical subtree CONSUMES state on read
    (e.g. DEVICE-mode exchange fetches close blocks after one pass) —
    such lineage is not re-runnable, so the scheduler disables
    speculation and crash-retry for stages over it."""
    if getattr(plan, "consuming", False):
        return True
    return any(tree_consuming(c) for c in getattr(plan, "children", []))


# ---------------------------------------------------------------- task

class Task:
    """One deterministic unit of a stage.

    - `run(attempt) -> result`: execute the lineage (thread backend).
    - `payload = ("module:function", args)`: picklable form for the
      process backend; args must fully describe the input split + plan
      fragment so any worker can recompute the partition.
    - `commit(result, attempt)`: called EXACTLY ONCE, for the winning
      attempt (publish staged shuffle output / record the result).
    - `abort(attempt)`: discard a losing/failed attempt's staged
      output. Must be idempotent.
    """

    __slots__ = ("index", "run", "payload", "commit", "abort", "lineage")

    def __init__(self, index: int,
                 run: Optional[Callable[[int], Any]] = None,
                 payload: Optional[Tuple[str, Any]] = None,
                 commit: Optional[Callable[[Any, int], None]] = None,
                 abort: Optional[Callable[[int], None]] = None,
                 lineage: str = ""):
        self.index = index
        self.run = run
        self.payload = payload
        self.commit = commit
        self.abort = abort
        self.lineage = lineage


# ------------------------------------------------------- thread backend

class ThreadBackend:
    """Virtual workers over a thread pool — the single-process engine's
    default. Worker ids are labels for the eviction bookkeeping; the
    pool itself is shared. `close()` abandons in-flight attempts
    (shutdown(wait=False)); a late completion self-aborts via the
    orphan callback, so losing speculative attempts never leak staged
    output."""

    def __init__(self, max_parallel: int = 8, name: str = "stage"):
        self._pool = ThreadPoolExecutor(
            max_workers=max(1, max_parallel),
            thread_name_prefix=f"sched-{name}")
        self._n = max(1, max_parallel)
        self._repl = itertools.count(0)
        self._q: "queue.Queue" = queue.Queue()
        self._lock = threading.Lock()
        self._closed = False

    def workers(self) -> List[str]:
        return [f"local-{i}" for i in range(self._n)]

    def parallelism(self) -> int:
        return self._n

    def replacement_worker(self) -> Optional[str]:
        # virtual workers are free: an evicted one is replaced so the
        # stage keeps its concurrency (a cluster manager restarting an
        # executor elsewhere)
        return f"local-r{next(self._repl)}"

    def submit(self, task: Task, attempt: int, worker: str,
               fn: Callable[[], Any], on_orphan: Callable, stage: int
               ) -> None:
        def _run():
            try:
                ev = ("ok", task.index, attempt, worker, fn(), stage)
            except WorkerLost as e:
                ev = ("lost", task.index, attempt, worker, e, stage)
            except InjectedFault as e:
                kind = "lost" if e.site == "worker.crash" else "err"
                ev = (kind, task.index, attempt, worker, e, stage)
            except BaseException as e:
                ev = ("err", task.index, attempt, worker, e, stage)
            with self._lock:
                if not self._closed:
                    self._q.put(ev)
                    return
            on_orphan(ev)

        self._pool.submit(_run)

    def poll(self, timeout: float):
        try:
            return self._q.get(timeout=timeout)
        except queue.Empty:
            return None

    def lost_workers(self) -> List[str]:
        return []

    def evict(self, worker: str) -> None:
        pass

    def close(self) -> List[tuple]:
        """Mark closed and return queued-but-unprocessed events (the
        caller aborts their output); in-flight attempts self-orphan."""
        with self._lock:
            self._closed = True
            drained = []
            while True:
                try:
                    drained.append(self._q.get_nowait())
                except queue.Empty:
                    break
        self._pool.shutdown(wait=False)
        return drained


# ------------------------------------------------------------ scheduler

class StageScheduler:
    """Drive one TaskSet to completion with retry, eviction and
    speculation. Results return in task-index order. Terminal failures
    (non-retryable exceptions, or a retryable one past the attempt
    budget) propagate after all in-flight attempts drain — no attempt
    outlives the stage with uncommitted side effects unaccounted."""

    _TICK_S = 0.02

    def __init__(self, conf=None, name: str = "stage", backend=None,
                 max_parallel: int = 8, rerunnable: bool = True):
        from spark_rapids_tpu.config import rapids_conf as rc

        def get(entry):
            return conf.get(entry) if conf is not None else entry.default

        self.name = name
        self.rerunnable = rerunnable
        self.max_attempts = max(1, int(get(rc.STAGE_MAX_ATTEMPTS)))
        if not rerunnable:
            self.max_attempts = 1
        self.spec_enabled = bool(get(rc.SPECULATION_ENABLED)) and \
            rerunnable
        self.spec_multiplier = float(get(rc.SPECULATION_MULTIPLIER))
        self.spec_quantile = float(get(rc.SPECULATION_QUANTILE))
        self.spec_min_s = float(get(rc.SPECULATION_MIN_RUNTIME_MS)) \
            / 1000.0
        # injected straggler stall: long enough to cross the
        # speculation threshold of any sanely-conf'd stage
        self.straggler_s = max(0.2, 2.0 * self.spec_min_s)
        self._backend = backend
        self._max_parallel = max(1, max_parallel)

    # --- attempt wrapper (chaos sites live here) ---

    def _attempt_fn(self, task: Task, attempt: int,
                    stage: int = 0, speculative: bool = False
                    ) -> Callable[[], Any]:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import cancellation, faults

        # capture the SUBMITTING thread's query identity and cancel
        # token here — pool threads have neither in their own
        # thread-local scope
        qid = obs_events.effective_query_id()
        token = cancellation.current()

        def fn():
            # the task scope tags every event emitted during the
            # attempt (operator spans above all) with its identity, so
            # the span builder hangs them under this attempt; the
            # cancellation scope re-establishes the query token so
            # every yield point inside the attempt sees it
            with obs_events.task_scope(stage, task.index, attempt,
                                       speculative, query_id=qid), \
                    cancellation.scope(token):
                if token is not None:
                    token.check()  # attempt boundary = yield point
                if self.rerunnable:
                    faults.maybe_inject(
                        "worker.crash",
                        detail=f"{self.name}[{task.index}] "
                               f"attempt {attempt}")
                    if faults.should_inject("task.straggler"):
                        # interruptible: a cancelled query must not
                        # ride out injected straggler latency
                        cancellation.sleep_interruptible(
                            self.straggler_s)
                return task.run(attempt)

        return fn

    @staticmethod
    def _commit(task: Task, result, attempt: int) -> None:
        if task.commit is not None:
            task.commit(result, attempt)

    @staticmethod
    def _abort(task: Task, attempt: int) -> None:
        if task.abort is not None:
            task.abort(attempt)

    @staticmethod
    def _result_rows(result) -> Optional[int]:
        """Row count of a committed result when it is host-side (an
        arrow table); device payloads would pay a sync — skip them."""
        rows = getattr(result, "num_rows", None)
        return rows if isinstance(rows, int) else None

    # --- single-task fast path (no pool) ---

    def _run_inline(self, task: Task) -> List[Any]:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import cancellation

        token = next(_stage_token)
        ctoken = cancellation.current()
        obs_events.emit("stage.start", stage=token, name=self.name,
                        tasks=1)
        last: Optional[BaseException] = None
        for attempt in range(self.max_attempts):
            stats.add("tasksLaunched")
            obs_events.emit("task.attempt.start", stage=token,
                            task=task.index, attempt=attempt,
                            worker="inline", speculative=False)
            t0 = time.monotonic()
            try:
                result = self._attempt_fn(task, attempt, token)()
                self._commit(task, result, attempt)
                obs_events.emit(
                    "task.attempt.end", stage=token, task=task.index,
                    attempt=attempt, status="ok",
                    wallMs=round((time.monotonic() - t0) * 1000, 3),
                    rows=self._result_rows(result))
                obs_events.emit("stage.end", stage=token,
                                name=self.name, status="ok")
                return [result]
            except BaseException as e:
                self._abort(task, attempt)
                lost = isinstance(e, WorkerLost) or (
                    isinstance(e, InjectedFault)
                    and e.site == "worker.crash")
                obs_events.emit(
                    "task.attempt.end", stage=token, task=task.index,
                    attempt=attempt, status="lost" if lost else "failed",
                    wallMs=round((time.monotonic() - t0) * 1000, 3))
                if not lost or attempt + 1 >= self.max_attempts:
                    obs_events.emit("stage.end", stage=token,
                                    name=self.name, status="failed")
                    raise
                last = e
                stats.add("evictedWorkers")
                stats.add("tasksRetried")
                stats.add("recomputedPartitions")
                if ctoken is not None:
                    # poison-query feed: a crash-looping query fails
                    # fast (QueryQuarantinedError) instead of burning
                    # the rest of its attempt budget
                    ctoken.record_worker_crash(token, task.index,
                                               "inline")
                    ctoken.check()
        raise last  # pragma: no cover (loop always returns or raises)

    # --- main driver ---

    def run(self, tasks: List[Task]) -> List[Any]:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import cancellation

        ctoken = cancellation.current()
        if not tasks:
            return []
        stats.add("stagesRun")
        if len(tasks) == 1 and self._backend is None:
            return self._run_inline(tasks[0])
        backend = self._backend or ThreadBackend(
            min(self._max_parallel, len(tasks)), self.name)
        owns_backend = self._backend is None
        token = next(_stage_token)
        obs_events.emit("stage.start", stage=token, name=self.name,
                        tasks=len(tasks))
        n = len(tasks)
        results: List[Any] = [None] * n
        committed = [False] * n
        launched = [0] * n
        running: Dict[Tuple[int, int], Tuple[str, float]] = {}
        speculative: set = set()
        durations: List[float] = []
        pending = deque(range(n))
        live = list(backend.workers())
        evicted: set = set()
        rr = itertools.count(0)
        terminal: Optional[BaseException] = None

        def pick_worker() -> Optional[str]:
            if not live:
                w = backend.replacement_worker()
                if w is None:
                    return None
                live.append(w)
            return live[next(rr) % len(live)]

        def launch(idx: int, is_spec: bool = False) -> bool:
            w = pick_worker()
            if w is None:
                return False
            attempt = launched[idx]
            launched[idx] += 1
            running[(idx, attempt)] = (w, time.monotonic())
            stats.add("tasksLaunched")
            if is_spec:
                stats.add("tasksSpeculated")
                speculative.add((idx, attempt))
            obs_events.emit("task.attempt.start", stage=token,
                            task=idx, attempt=attempt, worker=w,
                            speculative=is_spec)
            backend.submit(tasks[idx], attempt, w,
                           self._attempt_fn(tasks[idx], attempt, token,
                                            is_spec),
                           self._on_orphan(tasks, token), token)
            return True

        def emit_end(idx: int, attempt: int, status: str,
                     info=None, rows=None) -> None:
            wall = None if info is None else \
                round((time.monotonic() - info[1]) * 1000, 3)
            obs_events.emit("task.attempt.end", stage=token, task=idx,
                            attempt=attempt, status=status,
                            wallMs=wall, rows=rows)

        def evict_worker(w: str) -> None:
            if w in evicted:
                return
            evicted.add(w)
            if w in live:
                live.remove(w)
            backend.evict(w)
            stats.add("evictedWorkers")

        def handle(ev) -> None:
            nonlocal terminal
            kind, idx, attempt, w, value, ev_token = ev
            if ev_token != token:
                # a previous stage's straggling loser on a shared
                # backend: its output was already aborted/abandoned
                return
            info = running.pop((idx, attempt), None)
            if kind == "ok":
                if committed[idx] or terminal is not None:
                    self._abort(tasks[idx], attempt)
                    emit_end(idx, attempt, "discarded", info)
                    return
                committed[idx] = True
                if info is not None:
                    durations.append(time.monotonic() - info[1])
                if (idx, attempt) in speculative:
                    stats.add("speculativeWins")
                self._commit(tasks[idx], value, attempt)
                results[idx] = value
                emit_end(idx, attempt, "ok", info,
                         rows=self._result_rows(value))
                return
            # failed attempt: its staged output must go
            self._abort(tasks[idx], attempt)
            emit_end(idx, attempt,
                     "lost" if kind == "lost" else "failed", info)
            if kind == "lost":
                evict_worker(w)
                if ctoken is not None:
                    # poison-query quarantine feed: repeated crashes
                    # cancel the token; the next tick fails the stage
                    # fast with the crash history
                    ctoken.record_worker_crash(token, idx, w)
                if committed[idx] or terminal is not None:
                    return
                if any(k[0] == idx for k in running):
                    return  # a duplicate attempt is still in flight
                if launched[idx] >= self.max_attempts:
                    terminal = value if isinstance(value, BaseException) \
                        else WorkerLost(w, f"task {idx} attempt budget "
                                           f"exhausted")
                else:
                    stats.add("tasksRetried")
                    stats.add("recomputedPartitions")
                    pending.append(idx)
                return
            # kind == "err": not scheduler-retryable — each error class
            # has its own recovery owner (backoff, ladder, lost-output
            # recovery); masking it here would hide real bugs
            if not committed[idx] and terminal is None:
                terminal = value

        def maybe_speculate(now: float) -> None:
            if not self.spec_enabled:
                return
            need = max(1, math.ceil(self.spec_quantile * n))
            if len(durations) < need:
                return
            med = statistics.median(durations)
            threshold = max(self.spec_multiplier * med, self.spec_min_s)
            for (idx, attempt), (w, t0) in list(running.items()):
                if committed[idx] or launched[idx] >= self.max_attempts:
                    continue
                if sum(1 for k in running if k[0] == idx) > 1:
                    continue  # already speculated
                if now - t0 > threshold:
                    launch(idx, is_spec=True)

        try:
            while True:
                if ctoken is not None and terminal is None and \
                        (ctoken.cancelled or ctoken.expired):
                    # cancelled/expired query: stop launching, drain
                    # in-flight attempts (their own checks cut them
                    # short), abort their output, then raise
                    try:
                        ctoken.check()
                    except BaseException as e:
                        terminal = e
                while pending and terminal is None and \
                        len(running) < backend.parallelism():
                    if not launch(pending.popleft()):
                        terminal = WorkerLost(
                            "<none>", "no live workers remain")
                        break
                if terminal is None and all(committed):
                    break
                if terminal is not None and not running:
                    break
                ev = backend.poll(self._TICK_S)
                now = time.monotonic()
                for w in backend.lost_workers():
                    if w in evicted:
                        continue
                    attempts_on_w = [
                        k for k, (wk, _t) in running.items() if wk == w]
                    evict_worker(w)
                    for (idx, attempt) in attempts_on_w:
                        handle(("lost", idx, attempt, w,
                                WorkerLost(w, "liveness check"), token))
                if ev is not None:
                    handle(ev)
                maybe_speculate(now)
        finally:
            if owns_backend:
                for ev in backend.close():
                    kind, idx, attempt = ev[0], ev[1], ev[2]
                    if kind == "ok" and ev[5] == token:
                        self._abort(tasks[idx], attempt)
                        emit_end(idx, attempt, "discarded")
            obs_events.emit(
                "stage.end", stage=token, name=self.name,
                status="ok" if terminal is None else "failed")
        if terminal is not None:
            raise terminal
        return results

    def _on_orphan(self, tasks: List[Task], stage: int = 0) -> Callable:
        from spark_rapids_tpu.obs import events as obs_events

        def on_orphan(ev) -> None:
            kind, idx, attempt = ev[0], ev[1], ev[2]
            if kind == "ok":
                self._abort(tasks[idx], attempt)
                obs_events.emit("task.attempt.end", stage=stage,
                                task=idx, attempt=attempt,
                                status="discarded", wallMs=None,
                                rows=None)

        return on_orphan
