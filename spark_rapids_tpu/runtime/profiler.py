"""Tracing/profiling — the NVTX-range integration analog (reference
NvtxWithMetrics.scala:21-34 threads named ranges + metrics through every
operator; docs/dev/nvtx_profiling.md workflow).

On TPU the equivalents are jax.profiler traces (viewable in
TensorBoard/Perfetto) and TraceAnnotation named ranges. The session
exposes start/stop; operators annotate their partition execution so
device work attributes to plan nodes in the timeline."""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

_active = False
_lock = threading.Lock()


def start_trace(log_dir: str) -> None:
    """Begin a profiler session (jax.profiler.start_trace); view with
    TensorBoard or Perfetto."""
    global _active
    import jax

    with _lock:
        if not _active:
            jax.profiler.start_trace(log_dir)
            _active = True


def stop_trace() -> None:
    global _active
    import jax

    with _lock:
        if _active:
            jax.profiler.stop_trace()
            _active = False


def is_active() -> bool:
    return _active


@contextlib.contextmanager
def annotate(name: str):
    """Named range around operator work (NvtxWithMetrics role). Cheap
    enough to leave on unconditionally — annotations no-op outside a
    profiler session."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield


@contextlib.contextmanager
def annotate_with_metric(name: str, metric, span: Optional[dict] = None):
    """Named range COUPLED with a nanosecond metric — the exact
    NvtxWithMetrics contract (one scope, both the timeline range and
    the operator metric accumulate) — and, when the obs bus is armed,
    an `operator.span` event so the scope lands in the query's span
    tree (obs/spans.py). `span` supplies extra span fields (operator
    name override, device flag, rows); the thread's scheduler task
    scope is inherited by the event automatically."""
    import time as _time

    import jax

    from spark_rapids_tpu.obs import events as _events

    t0 = _time.monotonic_ns()
    try:
        with jax.profiler.TraceAnnotation(name):
            yield
    finally:
        dt = _time.monotonic_ns() - t0
        metric.add(dt)
        if _events.armed():
            fields = dict(span or {})
            fields.setdefault("operator", name)
            device = bool(fields.pop("device", False))
            _events.emit("operator.span", metric=metric.name,
                         wallNs=dt, deviceNs=dt if device else 0,
                         **fields)


def save_device_memory_profile(path: str) -> Optional[str]:
    """Write a pprof-format device memory profile (the OOM-dump role,
    reference RapidsConf.scala:403-414 gpuOomDumpDir + heap dumps).
    Returns the path, or None when the backend has no profile."""
    import jax

    try:
        jax.profiler.save_device_memory_profile(path)
        return path
    except Exception:
        return None


def dump_oom_state(dump_dir: str, reason: str,
                   catalog=None) -> Optional[str]:
    """On an unrecoverable device OOM: device memory profile + a JSON
    snapshot of the RAISING spill catalog (per-tier buffer
    sizes/priorities) so the failure is diagnosable after the fact."""
    import json
    import os
    import time

    try:
        os.makedirs(dump_dir, exist_ok=True)
        import uuid

        stamp = time.strftime("%Y%m%d-%H%M%S")
        # uuid keeps same-second dumps (split storms, threads) distinct
        base = os.path.join(dump_dir,
                            f"oom-{stamp}-{uuid.uuid4().hex[:8]}")
        if catalog is None:
            from spark_rapids_tpu.runtime.memory import get_catalog

            catalog = get_catalog()
        cat = catalog
        with cat._lock:
            bufs = [{"tier": b.tier.name, "bytes": b.size_bytes,
                     "priority": b._priority}
                    for b in cat._buffers.values()]
        state = {
            "reason": reason,
            "device_limit": cat.pool.limit,
            "device_reserved": cat.pool.reserved,
            "host_used": cat.host_used,
            "buffers": bufs,
            "metrics": dict(cat.metrics),
        }
        with open(base + ".json", "w") as f:
            json.dump(state, f, indent=2)
        save_device_memory_profile(base + ".prof")
        return base + ".json"
    except Exception:
        return None
