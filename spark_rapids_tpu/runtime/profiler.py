"""Tracing/profiling — the NVTX-range integration analog (reference
NvtxWithMetrics.scala:21-34 threads named ranges + metrics through every
operator; docs/dev/nvtx_profiling.md workflow).

On TPU the equivalents are jax.profiler traces (viewable in
TensorBoard/Perfetto) and TraceAnnotation named ranges. The session
exposes start/stop; operators annotate their partition execution so
device work attributes to plan nodes in the timeline."""

from __future__ import annotations

import contextlib
import threading

_active = False
_lock = threading.Lock()


def start_trace(log_dir: str) -> None:
    """Begin a profiler session (jax.profiler.start_trace); view with
    TensorBoard or Perfetto."""
    global _active
    import jax

    with _lock:
        if not _active:
            jax.profiler.start_trace(log_dir)
            _active = True


def stop_trace() -> None:
    global _active
    import jax

    with _lock:
        if _active:
            jax.profiler.stop_trace()
            _active = False


def is_active() -> bool:
    return _active


@contextlib.contextmanager
def annotate(name: str):
    """Named range around operator work (NvtxWithMetrics role). Cheap
    enough to leave on unconditionally — annotations no-op outside a
    profiler session."""
    import jax

    with jax.profiler.TraceAnnotation(name):
        yield
