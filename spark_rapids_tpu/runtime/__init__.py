from spark_rapids_tpu.runtime.errors import (  # noqa: F401
    TpuRetryOOM,
    TpuSplitAndRetryOOM,
    TpuOOMError,
)
from spark_rapids_tpu.runtime.memory import (  # noqa: F401
    DeviceMemoryPool,
    SpillCatalog,
    SpillableBatch,
    SpillPriority,
    get_catalog,
    initialize_memory,
    shutdown_memory,
)
from spark_rapids_tpu.runtime.retry import (  # noqa: F401
    with_retry,
    with_retry_no_split,
    split_spillable_in_half_by_rows,
)
from spark_rapids_tpu.runtime.semaphore import TpuSemaphore  # noqa: F401
from spark_rapids_tpu.runtime.metrics import TpuMetric, MetricsRegistry  # noqa: F401
