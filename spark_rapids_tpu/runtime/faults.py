"""Deterministic fault-injection registry — the chaos harness.

The reference plugin proves its recoverable-failure machinery with
forced-fault tests (the `*RetrySuite` strategy, SURVEY.md section 4
tier 2: RmmSpark injects OOMs at allocation points). This module
generalizes that discipline to EVERY failure domain of the engine:
injection SITES are declared as dotted names at the exact code
locations where the real world fails —

    io.read             file open/read in io/readers.py + io/avro.py
    shuffle.fetch       shuffle block file reads (shuffle/manager.py)
    shuffle.deserialize wire-format decode (shuffle/serde.py)
    compile.cache_load  persistent-cache artifact loads
                        (runtime/compile_cache.py)
    spill.disk          disk-tier spill writes/reads (runtime/memory.py)
    device.dispatch     fused/eager program dispatch (exec/fused.py,
                        api/dataframe.py) — the site that exercises the
                        degradation ladder end to end
    worker.crash        task-attempt launch in the stage scheduler
                        (runtime/scheduler.py) — the attempt dies as if
                        its worker was kill -9'd; the scheduler evicts
                        the worker and re-runs the partition
    task.straggler      task-attempt launch in the stage scheduler —
                        the attempt stalls instead of dying, exercising
                        speculative execution's duplicate-attempt +
                        commit-once path
    shuffle.lost_output shuffle block reads of attempt-tagged map
                        output (shuffle/manager.py) — the block is gone
                        AFTER the block-level retry budget, exercising
                        lineage recomputation of the owning map task
    query.cancel_race   query completion in the admission controller
                        (runtime/admission.py) — a cancel lands exactly
                        as the query finishes; the result must still
                        return, permits/slots release exactly once, and
                        the late cancel must not bleed into the next
                        query
    admission.slow_drain admission slot release — the handoff to the
                        next queued query is delayed, exercising
                        queue-wait accounting and queue-timeout margins
    semaphore.partial_hold
                        device-permit grant (runtime/semaphore.py) —
                        the granted task keeps holding while stalled
                        (interruptibly) for a beat, deterministically
                        widening the hold-and-wait window so the
                        legacy-acquisition deadlock gates form their
                        cycle on every run instead of relying on
                        scheduler timing
    device.fatal        fused/eager program dispatch and unspill H2D
                        (runtime/device_monitor.py guard sites) — a
                        FATAL runtime error, as if the PJRT client
                        died: the engine fences, cancels in-flight
                        queries with retryable DeviceLostError, warm-
                        recovers (epoch bump + backend rebuild + tier
                        restore) and resubmits once through admission
    device.lost_buffer  spill-catalog batch registration
                        (runtime/memory.py add_batch) — poisons ONE
                        device buffer's epoch so its next use hits the
                        stale-handle gate: the deterministic proof
                        that pre-epoch handles raise instead of
                        reading recycled device memory
    dcn.collective      multi-host SPMD dispatch
                        (parallel/plan_compiler.py) — a transient
                        cross-host (DCN) collective failure; bounded
                        retries per spark.rapids.tpu.multihost.
                        collectiveRetries before escalating to
                        host-loss handling
    host.fatal          multi-host SPMD dispatch — an entire HOST
                        (one process's worth of chips) dies
                        mid-collective: the mesh engine fences every
                        chip of that host in one step (fence_host),
                        rebuilds the mesh over the surviving hosts,
                        and recovers the lost shards from lineage
    stream.prefetch     staging-queue read in the streaming executor
                        (stream/executor.py) — a prefetched unit is
                        lost between decode and upload; the executor
                        re-enqueues that ScanUnit (bounded retries)
                        and the stream continues, proving partition-
                        granular retry without restarting the query
    stream.window_evict window-slot consume in the streaming executor
                        — the slot is forcibly spilled to host before
                        compute touches it, exercising the SpillCatalog
                        round trip (unspill-on-use) under window
                        pressure
    io.write            staged file write in the commit protocol
                        (io/commit.py stage_file) — the physical write
                        into a task attempt's staging dir fails; the
                        backoff loop re-writes the tmp file and the
                        atomic rename only ever publishes a complete
                        file into staging
    commit.task         task-commit promotion (io/commit.py) — the
                        rename of an attempt dir to its committed name
                        fails transiently; retried under backoff, and
                        first-commit-wins means a racing speculative
                        attempt can never double-publish
    commit.job          job-commit publish (io/commit.py commit_job) —
                        injected BEFORE any file becomes reader-visible;
                        an exhausted retry budget aborts the job with
                        staging unwound and pre-existing output (the
                        deferred overwrite swap) byte-identical
    commit.conflict     lakehouse version-file claim (lakehouse/delta.py
                        _commit, lakehouse/iceberg.py commit_metadata) —
                        a synthetic concurrent-commit conflict; the
                        optimistic-transaction loser re-reads the
                        snapshot, re-runs conflict semantics and retries
                        under backoff, billed to the query retry budget

and every site's CONSUMER survives the injected fault: backoff retries
(runtime/backoff.py), quarantine-and-recompile, or engine demotion.
CI re-runs a query subset with seeded injection at each site and
asserts results are identical to the clean run (ci/chaos_check.sh).

Determinism: each site owns its own `random.Random` stream seeded from
(chaos.seed, site name), so the injection sequence at one site never
depends on how calls interleave across sites — the same seed replays
the same faults for a fixed per-site call sequence.

Per-site policy grammar (conf `spark.rapids.tpu.chaos.sites`):

    site:p=0.05     inject each call with probability 0.05
    site:every=7    inject every 7th call (deterministic, no RNG)
    site:once       inject exactly the first call
    site            inject at chaos.defaultProbability

Multiple sites join with ';'. An empty spec with chaos.enabled=true
arms every KNOWN site at the default probability.
"""

from __future__ import annotations

import random
import threading
from typing import Dict, Optional, Tuple

KNOWN_SITES = (
    "io.read",
    "shuffle.fetch",
    "shuffle.deserialize",
    "compile.cache_load",
    "spill.disk",
    "device.dispatch",
    "worker.crash",
    "task.straggler",
    "shuffle.lost_output",
    "query.cancel_race",
    "admission.slow_drain",
    "semaphore.partial_hold",
    "device.fatal",
    "device.lost_buffer",
    "ici.collective",
    "chip.fatal",
    "dcn.collective",
    "host.fatal",
    "stream.prefetch",
    "stream.window_evict",
    "io.write",
    "commit.task",
    "commit.job",
    "commit.conflict",
)


class InjectedFault(RuntimeError):
    """A chaos-harness fault. Deliberately NOT a TpuOOMError: the OOM
    retry loops must not swallow it — each site's own recovery path
    (backoff, quarantine, degradation ladder) has to prove itself."""

    def __init__(self, site: str, detail: str = ""):
        self.site = site
        self.detail = detail
        msg = f"injected fault at {site}"
        if detail:
            msg += f" ({detail})"
        super().__init__(msg)


class SitePolicy:
    """One site's injection policy: probability | every-Nth | one-shot."""

    __slots__ = ("kind", "value")

    def __init__(self, kind: str, value: float = 0.0):
        if kind not in ("p", "every", "once"):
            raise ValueError(f"unknown chaos policy kind {kind!r}")
        self.kind = kind
        self.value = value

    def decide(self, rng: random.Random, call_index: int) -> bool:
        if self.kind == "once":
            return call_index == 1
        if self.kind == "every":
            n = max(1, int(self.value))
            return call_index % n == 0
        return rng.random() < float(self.value)

    def __repr__(self):
        if self.kind == "once":
            return "once"
        return f"{self.kind}={self.value}"


def parse_sites(spec: str, default_p: float) -> Dict[str, SitePolicy]:
    """'io.read:p=0.1;shuffle.fetch:every=3;compile.cache_load:once'
    -> {site: SitePolicy}. A bare site name takes the default
    probability. Unknown site names are allowed (future PRs declare new
    sites without touching the parser)."""
    out: Dict[str, SitePolicy] = {}
    for part in (spec or "").split(";"):
        part = part.strip()
        if not part:
            continue
        site, _, pol = part.partition(":")
        site = site.strip()
        pol = pol.strip()
        if not site:
            raise ValueError(f"empty site name in chaos spec {spec!r}")
        if not pol:
            out[site] = SitePolicy("p", default_p)
        elif pol == "once":
            out[site] = SitePolicy("once")
        elif pol.startswith("p="):
            p = float(pol[2:])
            if not 0.0 <= p <= 1.0:
                raise ValueError(f"chaos probability out of [0,1]: {pol}")
            out[site] = SitePolicy("p", p)
        elif pol.startswith("every="):
            out[site] = SitePolicy("every", int(pol[6:]))
        else:
            raise ValueError(f"unknown chaos policy {pol!r} for {site}")
    return out


class FaultRegistry:
    """Thread-safe registry of armed sites with per-site deterministic
    RNG streams and checked/injected counters."""

    def __init__(self, seed: int = 0,
                 policies: Optional[Dict[str, SitePolicy]] = None):
        self.seed = seed
        self._policies = dict(policies or {})
        self._rngs = {site: random.Random(f"{seed}:{site}")
                      for site in self._policies}
        self._calls: Dict[str, int] = {s: 0 for s in self._policies}
        self._injected: Dict[str, int] = {}
        self._lock = threading.Lock()

    @property
    def armed(self) -> bool:
        return bool(self._policies)

    def sites(self) -> Tuple[str, ...]:
        return tuple(sorted(self._policies))

    def should_inject(self, site: str) -> bool:
        pol = self._policies.get(site)
        if pol is None:
            return False
        with self._lock:
            self._calls[site] += 1
            hit = pol.decide(self._rngs[site], self._calls[site])
            if hit:
                self._injected[site] = self._injected.get(site, 0) + 1
        if hit:
            from spark_rapids_tpu.obs import events as obs_events

            obs_events.emit("chaos", site=site)
        return hit

    def maybe_inject(self, site: str, detail: str = "") -> None:
        if self.should_inject(site):
            raise InjectedFault(site, detail)

    def counters(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {site: {"checked": self._calls.get(site, 0),
                           "injected": self._injected.get(site, 0)}
                    for site in self._policies}


_DISABLED = FaultRegistry()
_registry: FaultRegistry = _DISABLED
_lock = threading.Lock()


def get() -> FaultRegistry:
    return _registry


def install(registry: FaultRegistry) -> FaultRegistry:
    """Swap the process registry (tests, session configure)."""
    global _registry
    with _lock:
        _registry = registry
    return registry


def configure(conf=None) -> FaultRegistry:
    """Session-lifecycle hook (plugin.py TpuExecutorPlugin.init): arm
    the registry per `spark.rapids.tpu.chaos.*` or disarm it."""
    from spark_rapids_tpu.config import rapids_conf as rc

    if conf is None or not conf.get(rc.CHAOS_ENABLED):
        return install(_DISABLED)
    default_p = conf.get(rc.CHAOS_DEFAULT_P)
    policies = parse_sites(conf.get(rc.CHAOS_SITES), default_p)
    if not policies:
        policies = {s: SitePolicy("p", default_p) for s in KNOWN_SITES}
    return install(FaultRegistry(conf.get(rc.CHAOS_SEED), policies))


def maybe_inject(site: str, detail: str = "") -> None:
    """Hot-path entry: a dict lookup + early return when disarmed."""
    reg = _registry
    if reg._policies:
        reg.maybe_inject(site, detail)


def should_inject(site: str) -> bool:
    reg = _registry
    return bool(reg._policies) and reg.should_inject(site)


def counters() -> Dict[str, Dict[str, int]]:
    return _registry.counters()
