"""Query admission control — the multi-tenant governance front door.

The device semaphore (runtime/semaphore.py) governs TASK concurrency
inside a query; nothing governed QUERIES. Under concurrent traffic a
second query could wedge behind the first's permits with no queueing
policy, no deadline, no cancel, and no per-query accounting — the
failure mode memory-aware engines design against (Theseus's admission
control over data movement, Vortex's explicit capacity management under
oversubscription; PAPERS.md). This module makes every query a
first-class governed unit:

- **Admission**: at most `admission.maxConcurrentQueries` queries
  execute; up to `admission.queue.maxDepth` more wait in a
  priority-then-FIFO queue (priority from `query.priority`); anything
  past that is load-shed IMMEDIATELY with QueryRejectedError carrying
  the running-query table. Queued queries time out after
  `admission.queue.timeoutMs` with the same diagnostics — a submission
  is never an unbounded wait.
- **Deadlines + cancellation**: every admitted query gets a CancelToken
  (runtime/cancellation.py) with `query.timeoutMs` as its deadline
  (queue wait counts); `cancel(query_id)` / `cancel_all()` cancel
  queued queries instantly and running queries at their next
  cooperative yield point.
- **Quarantine**: the token is also the poison-query ledger — worker
  crashes recorded by the stage scheduler trip
  `admission.quarantine.maxWorkerCrashes` into a fast
  QueryQuarantinedError with the crash history.

Re-entrancy mirrors the semaphore's per-task discipline: a nested
collect on a thread that already holds a slot (cache materialization,
writes that read) rides the enclosing query's admission, so nesting can
never self-deadlock the queue.

Observability: `admission.*` events (queued/admitted/shed/cancelled/
deadline/quarantined) land on the obs bus, an `AdmissionQueue` operator
span records the queue wait on the query's span tree, and the counter
ledger surfaces in `session.robustness_metrics["admission"]` and
bench.py's admission block. Chaos sites `admission.slow_drain` (delayed
slot handoff) and `query.cancel_race` (a cancel landing exactly at
completion) harden the drain and finish paths.
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import threading
import time
from collections import deque
from typing import Dict, List, Optional

from spark_rapids_tpu.runtime.cancellation import CancelToken
from spark_rapids_tpu.runtime.errors import (
    QueryDeadlineExceeded,
    QueryQuarantinedError,
    QueryQueueTimeout,
    QueryRejectedError,
)

# --------------------------------------------------------------- stats

_FIELDS = ("queriesSubmitted", "queriesAdmitted", "queriesQueued",
           "queriesShed", "queueTimeouts", "queriesCancelled",
           "deadlineExceeded", "queriesQuarantined")


class _AdmissionStats:
    """Process-wide admission ledger (the scheduler.stats pattern)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._v = {f: 0 for f in _FIELDS}
        self.queue_wait_ms_total = 0.0
        self.queue_wait_ms_max = 0.0
        self.cancel_latency_ms_max = 0.0
        self._waits = deque(maxlen=1024)
        self._cancel_lat = deque(maxlen=1024)

    def add(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._v[field] += n

    def record_wait(self, ms: float) -> None:
        with self._lock:
            self.queue_wait_ms_total += ms
            self.queue_wait_ms_max = max(self.queue_wait_ms_max, ms)
            self._waits.append(ms)

    def record_cancel_latency(self, ms: float) -> None:
        with self._lock:
            self.cancel_latency_ms_max = max(
                self.cancel_latency_ms_max, ms)
            self._cancel_lat.append(ms)

    @staticmethod
    def _pct(sorted_vals: List[float], q: float) -> float:
        if not sorted_vals:
            return 0.0
        i = min(len(sorted_vals) - 1,
                int(round(q * (len(sorted_vals) - 1))))
        return sorted_vals[i]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._v)
            waits = sorted(self._waits)
            lats = sorted(self._cancel_lat)
            out["queueWaitMsTotal"] = round(self.queue_wait_ms_total, 3)
            out["queueWaitMsMax"] = round(self.queue_wait_ms_max, 3)
            out["queueWaitMsP50"] = round(self._pct(waits, 0.50), 3)
            out["queueWaitMsP99"] = round(self._pct(waits, 0.99), 3)
            out["cancelLatencyMsMax"] = round(
                self.cancel_latency_ms_max, 3)
            out["cancelLatencyMsP50"] = round(self._pct(lats, 0.50), 3)
            out["cancelLatencyMsP99"] = round(self._pct(lats, 0.99), 3)
        return out


stats = _AdmissionStats()


# -------------------------------------------------------------- handle

class QueryHandle:
    """One governed query: identity, token, and lifecycle stamps."""

    __slots__ = ("query_id", "token", "priority", "description",
                 "submitted_at", "admitted_at", "finished_at", "state",
                 "thread_name", "queue_wait_ms")

    def __init__(self, query_id: int, token: CancelToken,
                 priority: int, description: str):
        self.query_id = query_id
        self.token = token
        self.priority = priority
        self.description = description
        self.submitted_at = time.monotonic()
        self.admitted_at: Optional[float] = None
        self.finished_at: Optional[float] = None
        self.state = "queued"
        self.thread_name = threading.current_thread().name
        self.queue_wait_ms = 0.0

    def row(self) -> dict:
        now = time.monotonic()
        anchor = self.admitted_at or self.submitted_at
        return {"queryId": self.query_id, "state": self.state,
                "priority": self.priority,
                "elapsedS": round(now - anchor, 3),
                "thread": self.thread_name,
                "description": self.description}


# ---------------------------------------------------------- controller

_tls = threading.local()


class AdmissionController:
    """Bounded priority/FIFO admission queue + cancel registry."""

    def __init__(self, enabled: bool = True, max_concurrent: int = 4,
                 queue_depth: int = 16, queue_timeout_ms: int = 120_000,
                 quarantine_crashes: int = 8):
        self.enabled = enabled
        self.max_concurrent = max(1, int(max_concurrent))
        self.queue_depth = max(0, int(queue_depth))
        self.queue_timeout_ms = max(0, int(queue_timeout_ms))
        self.quarantine_crashes = max(0, int(quarantine_crashes))
        self.draining = False
        self.drain_reason = ""
        self._cv = threading.Condition()
        self._running: Dict[int, QueryHandle] = {}
        self._finished: Dict[int, QueryHandle] = {}
        # heap of (-priority, fifo_seq, query_id); the handle map is
        # authoritative — a cancelled entry lazily pops as a ghost
        self._heap: List[tuple] = []
        self._queued: Dict[int, QueryHandle] = {}
        self._fifo = itertools.count(0)

    # --- diagnostics ---

    def running_table(self) -> List[dict]:
        with self._cv:
            return [h.row() for h in
                    sorted(self._running.values(),
                           key=lambda h: h.query_id)]

    def queued_table(self) -> List[dict]:
        with self._cv:
            return [h.row() for h in
                    sorted(self._queued.values(),
                           key=lambda h: h.query_id)]

    @staticmethod
    def _fence_mode() -> str:
        """Admission policy while the engine is FENCED for device-loss
        recovery (runtime/device_monitor.py): '' (not fenced) |
        'degrade' (admit; dispatch serves the CPU rung) | 'queue'
        (hold until the fence lifts) | 'shed' (reject at submit)."""
        from spark_rapids_tpu.runtime import device_monitor

        mon = device_monitor.get()
        return mon.fenced_admission if mon.fenced else ""

    def _capacity_diag(self) -> str:
        rows = ", ".join(
            f"query={r['queryId']} elapsed={r['elapsedS']}s "
            f"prio={r['priority']} [{r['description']}]"
            for r in self.running_table()) or "none"
        return (f"{len(self._running)}/{self.max_concurrent} running, "
                f"queue {len(self._queued)}/{self.queue_depth}; "
                f"running queries holding capacity: [{rows}]")

    # --- submission ---

    def submit(self, query_id: int, priority: int = 0,
               timeout_ms: int = 0, description: str = "") -> QueryHandle:
        """Admit (possibly after queueing) or shed. Returns a RUNNING
        handle; raises QueryRejectedError / QueryQueueTimeout /
        QueryCancelledError-family — never waits unboundedly (the queue
        timeout, the query deadline, and cancellation all break the
        wait)."""
        from spark_rapids_tpu.obs import events as obs_events

        token = CancelToken(query_id, timeout_ms=timeout_ms,
                            description=description,
                            quarantine_threshold=self.quarantine_crashes)
        handle = QueryHandle(query_id, token, priority, description)
        stats.add("queriesSubmitted")
        if self.draining:
            # drain shed precedes every other admission verdict
            # (including enabled=False): a draining engine accepts NO
            # new top-level queries, while already-queued queries keep
            # their slots/deadlines and in-flight queries' nested
            # collects ride their enclosing handle (they never reach
            # submit()).
            stats.add("queriesShed")
            obs_events.emit("admission.shed", queryId=query_id,
                            reason="draining",
                            running=len(self._running))
            raise QueryRejectedError(
                f"query {query_id} rejected: the engine is draining"
                f"{' (' + self.drain_reason + ')' if self.drain_reason else ''}; "
                f"no new submissions are accepted (queued queries keep "
                f"their slots)", reason="draining")
        if not self.enabled:
            from spark_rapids_tpu.runtime import sanitizer as _san

            with self._cv:
                handle.state = "running"
                handle.admitted_at = time.monotonic()
                self._running[query_id] = handle
            stats.add("queriesAdmitted")
            san = _san.active()
            if san is not None:
                san.acquired(_san.ADMISSION, query_id)
            return handle
        fence = self._fence_mode()
        if fence == "shed":
            from spark_rapids_tpu.runtime import device_monitor

            stats.add("queriesShed")
            obs_events.emit("admission.shed", queryId=query_id,
                            reason="device fenced",
                            running=len(self._running))
            raise QueryRejectedError(
                f"query {query_id} rejected: the engine is FENCED for "
                f"device-loss recovery (epoch "
                f"{device_monitor.get().epoch}, "
                f"device.recovery.fencedAdmission=shed); retry after "
                f"recovery", reason="device fenced")
        with self._cv:
            if len(self._running) < self.max_concurrent and \
                    not self._heap and fence != "queue":
                self._admit_locked(handle)
                return handle
            if len(self._queued) >= self.queue_depth:
                stats.add("queriesShed")
                diag = self._capacity_diag()
                obs_events.emit("admission.shed", queryId=query_id,
                                reason="queue full",
                                running=len(self._running))
                raise QueryRejectedError(
                    f"query {query_id} rejected (admission queue "
                    f"full): {diag}", reason="queue full")
            # enqueue
            self._queued[query_id] = handle
            heapq.heappush(self._heap,
                           (-priority, next(self._fifo), query_id))
            stats.add("queriesQueued")
            obs_events.emit("admission.queued", queryId=query_id,
                            depth=len(self._queued),
                            running=len(self._running))

        def wake():
            with self._cv:
                self._cv.notify_all()

        token.on_cancel(wake)
        # wait-for edge: this queued query waits on the slot class held
        # by every running query (runtime/sanitizer.py); a cycle
        # through admission can only close via another resource class,
        # but the edge makes the full wedge visible when it does
        from spark_rapids_tpu.runtime import sanitizer as _san

        san = _san.active()
        wait_rec = None
        if san is not None:
            wait_rec = san.begin_wait(_san.ADMISSION, query_id,
                                      token=token, wake=wake)
        queue_deadline = (
            None if self.queue_timeout_ms <= 0
            else time.monotonic() + self.queue_timeout_ms / 1000.0)
        try:
            with self._cv:
                while True:
                    if wait_rec is not None:
                        wait_rec.check()  # deadlock-victim exit
                    if token.cancelled or token.expired:
                        self._drop_queued_locked(query_id)
                        token.check()  # raises (turns expiry into cancel)
                    if len(self._running) < self.max_concurrent and \
                            self._front_locked() == query_id and \
                            self._fence_mode() != "queue":
                        self._pop_front_locked()
                        self._queued.pop(query_id, None)
                        self._admit_locked(handle)
                        return handle
                    wait_s = None
                    if queue_deadline is not None:
                        wait_s = queue_deadline - time.monotonic()
                        if wait_s <= 0:
                            self._drop_queued_locked(query_id)
                            stats.add("queueTimeouts")
                            stats.add("queriesShed")
                            diag = self._capacity_diag()
                            obs_events.emit(
                                "admission.shed", queryId=query_id,
                                reason="queue timeout",
                                running=len(self._running))
                            raise QueryQueueTimeout(
                                f"query {query_id} timed out after "
                                f"{self.queue_timeout_ms}ms in the "
                                f"admission queue: {diag}")
                    r = token.remaining_s()
                    if r is not None:
                        wait_s = r if wait_s is None else min(wait_s, r)
                        wait_s += 0.001
                    self._cv.wait(wait_s)
        except BaseException:
            with self._cv:
                self._drop_queued_locked(query_id)
                self._cv.notify_all()  # a new front may now be eligible
            raise
        finally:
            if wait_rec is not None:
                san.end_wait(wait_rec)
            token.remove_on_cancel(wake)

    def _front_locked(self) -> Optional[int]:
        while self._heap:
            qid = self._heap[0][2]
            if qid in self._queued:
                return qid
            heapq.heappop(self._heap)  # ghost of a dropped entry
        return None

    def _pop_front_locked(self) -> None:
        heapq.heappop(self._heap)

    def _drop_queued_locked(self, query_id: int) -> None:
        self._queued.pop(query_id, None)  # heap entry pops as a ghost

    def _admit_locked(self, handle: QueryHandle) -> None:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import sanitizer as _san

        handle.state = "running"
        handle.admitted_at = time.monotonic()
        handle.queue_wait_ms = round(
            (handle.admitted_at - handle.submitted_at) * 1000.0, 3)
        self._running[handle.query_id] = handle
        stats.add("queriesAdmitted")
        stats.record_wait(handle.queue_wait_ms)
        san = _san.active()
        if san is not None:
            san.acquired(_san.ADMISSION, handle.query_id)
        obs_events.emit("admission.admitted", queryId=handle.query_id,
                        waitMs=handle.queue_wait_ms)

    # --- completion ---

    def finish(self, handle: QueryHandle, status: str = "ok") -> None:
        """Release the slot and hand it to the next queued query.
        `status`: ok | error | cancelled | deadline | quarantined."""
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import cancellation, faults

        token = handle.token
        if status == "ok" and \
                faults.should_inject("query.cancel_race"):
            # a cancel racing with completion: the result already
            # exists, so the late cancel must change nothing — the
            # release below still runs exactly once
            token.cancel("injected query.cancel_race")
        lat = token.unwind_latency_s()
        if status in ("cancelled", "deadline", "quarantined") and \
                lat is not None:
            stats.record_cancel_latency(lat * 1000.0)
        if status == "cancelled":
            stats.add("queriesCancelled")
            obs_events.emit("admission.cancelled",
                            queryId=handle.query_id,
                            reason=token._reason,
                            latencyMs=round((lat or 0) * 1000.0, 3))
        elif status == "deadline":
            stats.add("deadlineExceeded")
            obs_events.emit("admission.deadline",
                            queryId=handle.query_id,
                            reason=token._reason,
                            latencyMs=round((lat or 0) * 1000.0, 3))
        elif status == "quarantined":
            stats.add("queriesQuarantined")
            obs_events.emit("admission.quarantined",
                            queryId=handle.query_id,
                            reason=token._reason,
                            crashes=len(token.crashes))
        slow = faults.should_inject("admission.slow_drain")
        if slow:
            # delayed handoff (never under the lock); interruptible so
            # a cancelled query's unwind never rides out chaos latency
            # (lint rule raw-sleep)
            cancellation.sleep_interruptible(0.02)
        from spark_rapids_tpu.runtime import sanitizer as _san

        san = _san.active()
        if san is not None and handle.state == "running":
            san.released(_san.ADMISSION, handle.query_id)
        with self._cv:
            handle.state = "done"
            handle.finished_at = time.monotonic()
            self._running.pop(handle.query_id, None)
            self._finished[handle.query_id] = handle
            if len(self._finished) > 256:
                for k in sorted(self._finished)[:-128]:
                    del self._finished[k]
            self._cv.notify_all()

    # --- drain API ---

    def begin_drain(self, reason: str = "") -> None:
        """Stop accepting NEW top-level submissions (they shed with
        QueryRejectedError reason='draining'). Already-queued queries
        keep their slots and deadlines and still admit as capacity
        frees; running queries (and their nested collects) are
        untouched. Idempotent; `end_drain` re-opens the front door."""
        with self._cv:
            self.draining = True
            self.drain_reason = reason

    def end_drain(self) -> None:
        with self._cv:
            self.draining = False
            self.drain_reason = ""

    def quiescent(self) -> bool:
        """True when nothing is running or queued (the drain-complete
        condition the serving layer polls)."""
        with self._cv:
            return not self._running and not self._queued

    # --- cancel API ---

    def cancel(self, query_id: int, reason: str = "cancelled by user"
               ) -> bool:
        """Cancel a running or queued query by id. True when the
        token newly latched (False: unknown id or already done)."""
        with self._cv:
            h = self._running.get(query_id) or self._queued.get(query_id)
        if h is None:
            return False
        return h.token.cancel(reason)

    def cancel_all(self, reason: str = "cancelled by user") -> int:
        with self._cv:
            handles = list(self._running.values()) + \
                list(self._queued.values())
        return sum(1 for h in handles if h.token.cancel(reason))

    def cancel_where(self, predicate, reason: str = "cancelled by user"
                     ) -> int:
        """Cancel the running/queued queries whose handle satisfies
        `predicate` — the tenant-scoped cancel surface of the serving
        layer (serve handles carry a `serve:<tenant>:<class>`
        description, so a tenant can only ever unwind its own work)."""
        with self._cv:
            handles = [h for h in list(self._running.values())
                       + list(self._queued.values()) if predicate(h)]
        return sum(1 for h in handles if h.token.cancel(reason))

    def cancel_running(self, reason: str, error_cls=None) -> int:
        """Cancel only the RUNNING queries (the device-loss fence:
        queued queries never touched the dead device — they keep their
        queue positions and run after recovery). `error_cls` lets the
        fence unwind them with a retryable DeviceLostError instead of
        plain QueryCancelledError."""
        from spark_rapids_tpu.runtime.errors import QueryCancelledError

        with self._cv:
            handles = list(self._running.values())
        cls = error_cls or QueryCancelledError
        return sum(1 for h in handles
                   if h.token.cancel(reason, error_cls=cls))

    def status(self) -> dict:
        return {"running": self.running_table(),
                "queued": self.queued_table(),
                "maxConcurrentQueries": self.max_concurrent,
                "queueMaxDepth": self.queue_depth,
                "draining": self.draining}

    def load(self) -> dict:
        """Cheap numeric load signal for the fleet layer: surfaced
        through /readyz so the router can shed toward the least-loaded
        replica and back off one that is saturating (queriesShed is
        cumulative — the router watches its derivative)."""
        with self._cv:
            running = len(self._running)
            queued = len(self._queued)
        return {"running": running, "queued": queued,
                "maxConcurrentQueries": self.max_concurrent,
                "queueMaxDepth": self.queue_depth,
                "queriesShed": stats.snapshot().get("queriesShed", 0),
                "draining": bool(self.draining)}


# ------------------------------------------------------ process wiring

_controller = AdmissionController()
_lock = threading.Lock()


def get() -> AdmissionController:
    return _controller


def install(controller: AdmissionController) -> AdmissionController:
    """Swap the process controller (tests, bench's governed burst)."""
    global _controller
    with _lock:
        _controller = controller
    return controller


def configure(conf=None) -> AdmissionController:
    """Session-lifecycle hook (plugin.py TpuExecutorPlugin.init):
    rebuild the controller from spark.rapids.tpu.admission.* — running
    queries of a prior controller keep their handles/tokens; only the
    queue policy is fresh."""
    global _controller
    from spark_rapids_tpu.config import rapids_conf as rc

    def get_(entry):
        return conf.get(entry) if conf is not None else entry.default

    with _lock:
        old = _controller
        _controller = AdmissionController(
            enabled=bool(get_(rc.ADMISSION_ENABLED)),
            max_concurrent=get_(rc.ADMISSION_MAX_CONCURRENT),
            queue_depth=get_(rc.ADMISSION_QUEUE_DEPTH),
            queue_timeout_ms=get_(rc.ADMISSION_QUEUE_TIMEOUT_MS),
            quarantine_crashes=get_(rc.ADMISSION_QUARANTINE_CRASHES))
    # nobody will ever drain the replaced controller's queue again —
    # cancel its queued tokens so their waiters unwind cleanly instead
    # of waiting out a timeout (or forever)
    with old._cv:
        queued = list(old._queued.values())
    for h in queued:
        h.token.cancel("admission controller reconfigured while queued")
    return _controller


# ----------------------------------------------------- session surface

@contextlib.contextmanager
def request_overrides(priority: Optional[int] = None,
                      timeout_ms: Optional[int] = None,
                      description: Optional[str] = None):
    """Per-REQUEST admission parameters for this thread: the serving
    layer (serve/server.py) runs many concurrent queries with distinct
    priority classes through ONE session, so the session-wide
    query.priority / query.timeoutMs confs would race across
    connections. AdmissionScope consults the innermost active override
    before falling back to the session conf. Nests; None fields fall
    through to the next level."""
    prev = getattr(_tls, "overrides", None)
    ov = dict(prev or {})
    if priority is not None:
        ov["priority"] = int(priority)
    if timeout_ms is not None:
        ov["timeout_ms"] = int(timeout_ms)
    if description is not None:
        ov["description"] = str(description)
    _tls.overrides = ov
    try:
        yield ov
    finally:
        _tls.overrides = prev


def current_overrides() -> dict:
    return getattr(_tls, "overrides", None) or {}


class AdmissionScope:
    """Context manager the collect path enters around a query
    (api/dataframe.py): re-entrant per thread — a nested collect rides
    the enclosing query's handle/token — and maps the exit exception
    onto the admission finish status."""

    def __init__(self, session, description: str = ""):
        self.session = session
        self.description = description
        self.handle: Optional[QueryHandle] = None
        self.nested = False
        self._cancel_scope = None
        self._ctrl: Optional[AdmissionController] = None

    def __enter__(self) -> QueryHandle:
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import cancellation

        outer = getattr(_tls, "handle", None)
        if outer is not None:
            self.nested = True
            self.handle = outer
            return outer
        conf = self.session.rapids_conf
        # pin the controller that admits us: the slot must release on
        # the SAME controller even if a new session swaps the process
        # one while this query runs
        self._ctrl = get()
        qid = obs_events.allocate_query_id()
        ov = current_overrides()
        self.handle = self._ctrl.submit(
            qid,
            priority=ov.get("priority", conf.get(rc.QUERY_PRIORITY)),
            timeout_ms=ov.get("timeout_ms",
                              conf.get(rc.QUERY_TIMEOUT_MS)),
            description=ov.get("description", self.description))
        _tls.handle = self.handle
        self._cancel_scope = cancellation.scope(self.handle.token)
        self._cancel_scope.__enter__()
        return self.handle

    def __exit__(self, exc_type, exc, tb) -> bool:
        if self.nested:
            return False
        _tls.handle = None
        if self._cancel_scope is not None:
            self._cancel_scope.__exit__(exc_type, exc, tb)
        if exc is None:
            status = "ok"
        elif isinstance(exc, QueryQuarantinedError):
            status = "quarantined"
        elif isinstance(exc, QueryDeadlineExceeded):
            status = "deadline"
        elif self.handle.token.cancelled:
            status = "cancelled"
        else:
            status = "error"
        (self._ctrl or get()).finish(self.handle, status)
        return False


def current_handle() -> Optional[QueryHandle]:
    return getattr(_tls, "handle", None)
