"""Retry / split-and-retry execution — the RmmRapidsRetryIterator analog.

Reference semantics (`RmmRapidsRetryIterator.scala:62-197`):
- `withRetry(input, splitPolicy)(fn)`: run fn over a spillable input;
  on GpuRetryOOM re-run the same attempt (the spill already happened);
  on GpuSplitAndRetryOOM split the input (usually halving rows) and
  process the pieces, possibly splitting again, with a bound.
- `withRetryNoSplit`: same but split is not legal (fn not splittable).
- Inputs must be spillable so a retry can rematerialize them.

Here fn takes a SpillableBatch and returns a result; results are yielded
as a generator exactly like the reference's iterator contract.
"""

from __future__ import annotations

from collections import deque
from typing import Callable, Iterator, List, Optional, TypeVar

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import ColumnBatch, next_capacity
from spark_rapids_tpu.runtime.errors import (
    TpuOOMError,
    TpuRetryOOM,
    TpuSplitAndRetryOOM,
)
from spark_rapids_tpu.runtime.memory import (
    SpillableBatch,
    SpillPriority,
    get_catalog,
)

T = TypeVar("T")


def split_spillable_in_half_by_rows(sb: SpillableBatch
                                    ) -> List[SpillableBatch]:
    """The default split policy (reference splitSpillableInHalfByRows,
    used e.g. GpuAggregateExec.scala:306)."""
    catalog = get_catalog()
    batch = sb.get_batch()
    n = sb.row_count()
    if n <= 1:
        raise TpuOOMError("cannot split a batch of <=1 rows further")
    half = n // 2
    first = _slice_rows(batch, 0, half)
    second = _slice_rows(batch, half, n - half)
    out = [catalog.add_batch(first, SpillPriority.ACTIVE_ON_DECK),
           catalog.add_batch(second, SpillPriority.ACTIVE_ON_DECK)]
    sb.close()
    return out


def _slice_rows(batch: ColumnBatch, start: int, count: int) -> ColumnBatch:
    cap = next_capacity(count)
    idx = jnp.arange(cap, dtype=jnp.int32) + start
    idx = jnp.clip(idx, 0, batch.capacity - 1)
    return batch.gather(idx, count)


def with_retry(
    inputs,
    fn: Callable[[SpillableBatch], T],
    split_policy: Optional[Callable[[SpillableBatch],
                                    List[SpillableBatch]]] =
        split_spillable_in_half_by_rows,
    split_limit: int = 16,
) -> Iterator[T]:
    """Run fn over each spillable input with OOM retry/split semantics.

    fn MUST be idempotent w.r.t. its input (it can be called again with
    the same SpillableBatch after a TpuRetryOOM) and must not close its
    input — the framework does.
    """
    from spark_rapids_tpu.runtime import cancellation
    from spark_rapids_tpu.runtime.errors import QueryCancelledError

    if isinstance(inputs, SpillableBatch):
        inputs = [inputs]
    queue = deque(inputs)
    while queue:
        sb = queue.popleft()
        splits = 0
        while True:
            try:
                # split/retry iteration = a cooperative yield point: a
                # cancelled query must not keep splitting
                cancellation.check_current()
                result = fn(sb)
                sb.close()
                yield result
                break
            except QueryCancelledError:
                # checked here or raised from a yield point inside fn:
                # close the current piece AND everything still queued
                # so the spill catalog stays leak-free on cancel
                sb.close()
                for p in queue:
                    p.close()
                raise
            except TpuSplitAndRetryOOM:
                if split_policy is None:
                    sb.close()
                    raise
                splits += 1
                if splits > split_limit:
                    sb.close()
                    dump_terminal_oom(
                        f"split limit {split_limit} exceeded")
                    raise TpuOOMError(
                        f"split limit {split_limit} exceeded")
                pieces = split_policy(sb)
                # process first piece now, queue the rest in order
                sb = pieces[0]
                for p in reversed(pieces[1:]):
                    queue.appendleft(p)
            except TpuRetryOOM:
                continue  # spill already happened; same attempt again


def with_retry_no_split(sb: SpillableBatch, fn: Callable[[SpillableBatch], T]
                        ) -> T:
    """withRetryNoSplit: retries on TpuRetryOOM, propagates split OOMs."""
    out = next(with_retry([sb], fn, split_policy=None))
    return out


def dump_terminal_oom(reason: str) -> None:
    """Post-mortem dump at a TERMINAL OOM (retry/split budget
    exhausted): when spark.rapids.memory.gpu.oomDumpDir is set, write
    the memory-state snapshot (runtime/profiler.py). Recoverable
    retry-class OOMs never dump — they are normal execution events."""
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.config import rapids_conf as rc

    s = TpuSparkSession.active()
    dump_dir = s.rapids_conf.get(rc.OOM_DUMP_DIR) if s else ""
    if dump_dir:
        from spark_rapids_tpu.runtime import profiler

        profiler.dump_oom_state(dump_dir, reason)


class Retryable:
    """Checkpoint/restore contract for state mutated inside a retried
    block — the `com.nvidia.spark.Retryable` role
    (sql-plugin-api Retryable.java:22; used by withRestoreOnRetry,
    RmmRapidsRetryIterator.scala:234-261). Implementations snapshot
    whatever an OOM-triggered re-attempt must not observe half-updated:
    RNG streams, accumulated buffers, offsets."""

    def checkpoint(self) -> None:
        raise NotImplementedError

    def restore(self) -> None:
        raise NotImplementedError


class CheckpointedValue(Retryable):
    """Single mutable value with snapshot semantics."""

    def __init__(self, value):
        self.value = value
        self._mark = value

    def checkpoint(self) -> None:
        self._mark = self.value

    def restore(self) -> None:
        self.value = self._mark


class PendingBatches(Retryable):
    """Spillable-batch accumulator whose restore CLOSES anything
    appended since the checkpoint — partial appends from an aborted
    attempt neither leak spill-catalog entries nor double-count when
    the attempt re-runs."""

    def __init__(self):
        self.items: List[SpillableBatch] = []
        self.rows = 0
        self._mark = (0, 0)

    def append(self, sb: SpillableBatch, rows: int) -> None:
        self.items.append(sb)
        self.rows += rows

    def checkpoint(self) -> None:
        self._mark = (len(self.items), self.rows)

    def restore(self) -> None:
        k, r = self._mark
        for sb in self.items[k:]:
            sb.close()
        del self.items[k:]
        self.rows = r

    def close(self) -> None:
        for sb in self.items:
            sb.close()
        self.items.clear()
        self.rows = 0


def with_restore_on_retry(retryables, fn: Callable[[], T]) -> T:
    """Run fn with restore-on-retry semantics
    (RmmRapidsRetryIterator.scala:234-261 withRestoreOnRetry):
    checkpoint every retryable first; if a retry-class OOM escapes fn,
    restore them all before re-raising so the ENCLOSING retry loop
    re-attempts against clean state. Non-OOM exceptions also restore —
    a failed attempt must never leave half-applied state behind."""
    if isinstance(retryables, Retryable):
        retryables = [retryables]
    for r in retryables:
        r.checkpoint()
    try:
        return fn()
    except BaseException:
        for r in retryables:
            r.restore()
        raise


def retry_on_oom(fn: Callable[[], T], max_attempts: int = 8) -> T:
    """Re-attempt a non-splittable device step after TpuRetryOOM (the
    spill already freed memory); propagate split OOMs and give up after
    max_attempts."""
    from spark_rapids_tpu.runtime import cancellation

    attempts = 0
    while True:
        cancellation.check_current()
        try:
            return fn()
        except TpuRetryOOM as e:
            attempts += 1
            if attempts >= max_attempts:
                dump_terminal_oom(
                    f"retry budget exhausted after {attempts}: {e}")
                raise
