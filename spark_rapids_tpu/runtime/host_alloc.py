"""Bounded host-memory arbiter — the HostAlloc role (reference
HostAlloc.scala:349 + PinnedMemoryPool: every sizable host allocation
— reader decode buffers, shuffle staging, spilled device buffers —
draws from bounded pinned/pageable pools with blocking and retry
semantics instead of growing the heap unboundedly).

TPU mapping: PJRT stages transfers internally, so "pinned" is the
transfer-staging budget (advisory for placement, exact for
accounting) and "pageable" is general host working memory. The spill
catalog's HOST tier draws from the pageable pool, so spill pressure
and transient staging share ONE global host budget the way the
reference shares HostAlloc between spill stores and readers.

Semantics (HostAlloc.scala blocking-alloc):
- try_reserve: non-blocking.
- reserve(nbytes, timeout): wait for concurrent releases; on timeout,
  ask the spill catalog to push host-tier buffers to disk; if still
  over budget raise TpuRetryOOM (the CpuRetryOOM analog) so the
  caller's retry loop re-attempts smaller/later.
"""

from __future__ import annotations

import contextlib
import threading
from typing import Optional

from spark_rapids_tpu.runtime.errors import TpuRetryOOM


class HostPool:
    def __init__(self, limit: int, name: str,
                 has_spill_valve: bool = False):
        self.limit = int(limit)
        self.name = name
        self.used = 0
        self._cv = threading.Condition()
        # only the pageable pool can free bytes by pushing the spill
        # catalog's HOST tier to disk; the pinned pool has no valve
        self._has_spill_valve = has_spill_valve

    def resize(self, limit: int) -> None:
        """Adjust the limit in place (session re-init) — the pool
        OBJECT is stable so outstanding reservations release against
        the same ledger they reserved from."""
        with self._cv:
            self.limit = int(limit)
            self._cv.notify_all()

    def try_reserve(self, nbytes: int) -> bool:
        with self._cv:
            if self.used + nbytes <= self.limit:
                self.used += nbytes
                return True
            return False

    def reserve_force(self, nbytes: int) -> None:
        """Unconditional reservation (may exceed the limit): used by
        must-proceed paths (device spill relieving HBM pressure) so
        the ledger stays truthful and later callers see the pressure
        instead of the pool being silently bypassed."""
        with self._cv:
            self.used += nbytes

    def reserve(self, nbytes: int, timeout: float = 10.0) -> None:
        if nbytes > self.limit:
            raise TpuRetryOOM(
                f"host {self.name} pool too small: {nbytes} > "
                f"{self.limit}")
        deadline = None
        with self._cv:
            while self.used + nbytes > self.limit:
                import time

                if deadline is None:
                    deadline = time.monotonic() + timeout
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    break
                self._cv.wait(remaining)
            else:
                self.used += nbytes
                return
        if self._has_spill_valve:
            # timed out: push spilled host buffers to disk, then retry
            from spark_rapids_tpu.runtime.memory import get_catalog

            get_catalog().spill_host_bytes(nbytes)
            with self._cv:
                if self.used + nbytes <= self.limit:
                    self.used += nbytes
                    return
        raise TpuRetryOOM(
            f"host {self.name} pool exhausted reserving {nbytes} "
            f"(used={self.used}, limit={self.limit})")

    def release(self, nbytes: int) -> None:
        with self._cv:
            self.used -= nbytes
            self._cv.notify_all()


class HostAlloc:
    def __init__(self, pinned_limit: int, pageable_limit: int):
        self.pinned = HostPool(pinned_limit, "pinned")
        self.pageable = HostPool(pageable_limit, "pageable",
                                 has_spill_valve=True)

    def pool(self, pinned: bool) -> HostPool:
        return self.pinned if pinned else self.pageable

    @contextlib.contextmanager
    def reserved(self, nbytes: int, pinned: bool = False,
                 timeout: float = 10.0):
        pool = self.pool(pinned)
        # transfer staging larger than the whole pool serializes at
        # the full budget instead of failing (the pool bounds
        # CONCURRENCY; a single oversized transfer is legal)
        pool.reserve(min(nbytes, pool.limit), timeout)
        try:
            yield
        finally:
            pool.release(min(nbytes, pool.limit))


_instance: Optional[HostAlloc] = None
_lock = threading.Lock()


def initialize(pinned_limit: int, pageable_limit: int) -> None:
    """Install/resize the global pools. Pool OBJECTS are stable across
    re-initialization (sessions re-init with their confs) so
    reservations outstanding from earlier sessions release against the
    ledger they drew from."""
    global _instance
    with _lock:
        if _instance is None:
            _instance = HostAlloc(pinned_limit, pageable_limit)
        else:
            _instance.pinned.resize(pinned_limit)
            _instance.pageable.resize(pageable_limit)


def get() -> HostAlloc:
    global _instance
    with _lock:
        if _instance is None:
            _instance = HostAlloc(2 << 30, 8 << 30)
        return _instance
