"""OOM taxonomy — the GpuRetryOOM / GpuSplitAndRetryOOM analog.

The reference's spark-rapids-jni RmmSpark injects these as thread-
targeted exceptions when the RMM pool cannot satisfy an allocation
(`RmmRapidsRetryIterator.scala:194-197`). Here the reservation-based
DeviceMemoryPool raises them synchronously at reservation points, which
gives the same control flow without needing allocator callbacks from
PJRT (SURVEY.md section 7 hard part #3).
"""


class TpuOOMError(MemoryError):
    """Unrecoverable device OOM (after retry/split budget exhausted)."""


class TpuRetryOOM(TpuOOMError):
    """Transient: spill happened or may happen; roll back to the last
    checkpoint and re-execute the same work."""


class TpuSplitAndRetryOOM(TpuOOMError):
    """The work unit cannot fit even after spilling: split the input
    (usually in half by rows) and retry the pieces."""


class StringWidthExceeded(ValueError):
    """A string column's longest value exceeds
    spark.rapids.tpu.string.maxBytes — the padded-matrix device layout
    would multiply the column footprint. The engine dispatch catches
    this and re-runs the query on the CPU plan (a DATA-shape fallback,
    recorded like any other engine fallback)."""


class EngineIOError(RuntimeError):
    """Base for clean engine-surfaced I/O failures: a failure domain
    that exhausted its recovery budget reports WHAT failed in engine
    terms (buffer/block/file identity) instead of leaking a raw
    OSError/numpy error through an operator."""


class RetryExhausted(EngineIOError):
    """A backoff loop (runtime/backoff.py) ran out of attempts; chained
    to the last underlying error. Domain consumers convert it to their
    specific error class below."""


class ShuffleChecksumError(EngineIOError):
    """A shuffle block's per-block CRC did not match on deserialize —
    torn write, bit rot, or an injected shuffle.deserialize fault. The
    shuffle manager retries the fetch/decode before surfacing this."""


class ShuffleFetchError(EngineIOError):
    """A shuffle block could not be fetched/decoded after the retry
    budget; names the (shuffle_id, reduce_pid) block. When the lost
    block was written by an attempt-tagged map task, `map_id` names the
    owning map partition so the stage scheduler can recompute exactly
    that task from its lineage (runtime/scheduler.py)."""

    def __init__(self, msg: str, map_id=None):
        self.map_id = map_id
        super().__init__(msg)


class WorkerLost(RuntimeError):
    """A task attempt's worker died under it — process crash, heartbeat
    expiry, or an injected worker.crash fault. Retryable: the stage
    scheduler evicts the worker and re-runs the in-flight partitions
    elsewhere (the FetchFailed/ExecutorLost recovery role of Spark's
    DAGScheduler)."""

    def __init__(self, worker_id: str, detail: str = ""):
        self.worker_id = worker_id
        msg = f"worker {worker_id} lost"
        if detail:
            msg += f": {detail}"
        super().__init__(msg)


class SpillFileError(EngineIOError):
    """A disk-tier spill file is missing or unreadable; names the
    buffer id, tier, and path (never a raw numpy/OSError)."""

    def __init__(self, buffer_id: str, tier: str, path: str,
                 op: str = "read"):
        self.buffer_id = buffer_id
        self.tier = tier
        self.path = path
        super().__init__(
            f"spill {op} failed for buffer {buffer_id} "
            f"(tier {tier}): {path}")


class SemaphoreTimeout(RuntimeError):
    """Task-admission semaphore acquisition exceeded the conf'd
    timeout; the message carries held-permit diagnostics instead of the
    process hanging silently."""


class QueryGovernanceError(RuntimeError):
    """Base of the query-lifecycle governance taxonomy
    (runtime/admission.py + runtime/cancellation.py): every way the
    governance layer refuses or unwinds a query is a subclass, so
    callers can catch the whole family or one verdict."""


class QueryRejectedError(QueryGovernanceError):
    """Load shed at submission: the admission queue is at maxDepth on
    top of maxConcurrentQueries running. The message carries the
    running-query table (query ids, elapsed time, descriptions) so the
    operator sees WHO holds capacity — a shed is always an immediate
    clean error, never an unbounded wait. `reason` is a stable
    machine-readable verdict ("queue full" | "queue timeout" |
    "draining" | "device fenced" | "tenant quota") so the serving
    layer (serve/protocol.py) can map sheds onto wire error codes
    without parsing the human diagnostics."""

    def __init__(self, message: str = "", reason: str = ""):
        super().__init__(message)
        self.reason = reason or "rejected"


class QueryQueueTimeout(QueryRejectedError):
    """A queued query waited past admission.queue.timeoutMs without a
    slot freeing; diagnostics name the running queries that held
    capacity the whole time."""

    def __init__(self, message: str = "", reason: str = "queue timeout"):
        super().__init__(message, reason=reason)


class QueryCancelledError(QueryGovernanceError):
    """The query's CancelToken was cancelled (session.cancel(),
    cancel_all(), or a governance verdict); raised at the next
    cooperative yield point so the query unwinds within a bounded
    latency, releasing permits and spill-catalog buffers."""


class DeadlockDetectedError(QueryCancelledError):
    """The concurrency sanitizer (runtime/sanitizer.py) found this
    query in a wait-for cycle and selected it as the victim: the
    message names the full cycle (query ids, the resources each holds
    and waits on, hold durations). Cancellation semantics — the victim
    unwinds at its next yield point releasing every permit and buffer —
    and the collect path may transparently retry it once the cycle's
    survivors drain (sanitizer.deadlock.retryVictim)."""


class QueryDeadlineExceeded(QueryCancelledError):
    """The query ran past spark.rapids.tpu.query.timeoutMs (queue wait
    counts); cancellation semantics, with the deadline in the message."""


class DeviceLostError(QueryCancelledError):
    """The TPU runtime died under this query — a fatal PJRT/XLA error
    at a dispatch/transfer site, or a stale device handle from a
    previous device epoch (runtime/device_monitor.py). Cancellation
    semantics: the query unwinds at its next yield point releasing
    every permit and buffer, the engine fences and performs warm
    recovery (epoch bump, backend rebuild, tier restore), and the
    outermost collect resubmits the query once through admission
    (device.recovery.resubmit — the sanitizer's retryVictim pattern).
    `epoch` is the device epoch the failed work was stamped with."""

    def __init__(self, msg: str, epoch: int = None):
        self.epoch = epoch
        super().__init__(msg)


class QueryQuarantinedError(QueryCancelledError):
    """Poison-query quarantine: the query's attempts crashed workers
    (scheduler eviction feed) more than
    admission.quarantine.maxWorkerCrashes times — it is failed fast
    with its crash history instead of burning stage.maxAttempts
    budgets forever."""


class TpuAnsiError(ValueError):
    """ANSI-mode runtime error (the SparkArithmeticException /
    SparkDateTimeException role): raised when spark.sql.ansi.enabled
    turns wrap/null semantics into errors. Device operators detect the
    condition with a compiled overflow-mask reduction
    (expr/ansicheck.py) and raise host-side; the CPU oracle raises the
    same classes so differential tests compare error classes."""


class TpuArithmeticOverflow(TpuAnsiError):
    """[ARITHMETIC_OVERFLOW] add/subtract/multiply/negate/abs overflow."""


class TpuDivideByZero(TpuAnsiError):
    """[DIVIDE_BY_ZERO] division or remainder by zero."""


class TpuCastError(TpuAnsiError):
    """[CAST_OVERFLOW] / [CAST_INVALID_INPUT] ANSI cast failure (device
    numeric casts and the CPU oracle's CastError share this base)."""
