"""Operator metrics — the GpuMetric analog (GpuExec.scala:49-330).

Levels ESSENTIAL/MODERATE/DEBUG mirror `RapidsConf.scala:674`; standard
names match the reference so dashboards translate: numOutputRows,
numOutputBatches, opTime, semaphoreWaitTime, spillToHostTime, ...
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
SPILL_TIME = "spillTime"
BUILD_TIME = "buildTime"
JOIN_TIME = "joinTime"
BLOOM_FILTERED_ROWS = "bloomFilteredRows"
SORT_TIME = "sortTime"
AGG_TIME = "aggTime"
FILTER_TIME = "filterTime"
PARTITION_TIME = "partitionTime"
WINDOW_TIME = "windowTime"
TASK_TIME = "taskTime"


class TpuMetric:
    __slots__ = ("name", "level", "value", "_lock")

    def __init__(self, name: str, level: int = MODERATE):
        self.name = name
        self.level = level
        self.value = 0
        self._lock = threading.Lock()

    def add(self, v: int):
        with self._lock:
            self.value += int(v)

    @contextmanager
    def ns(self):
        """Nanosecond-scoped timing (GpuExec.scala:134 `ns` helper)."""
        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            self.add(time.monotonic_ns() - t0)


class MetricsRegistry:
    """Per-operator metric set."""

    def __init__(self, level: int = MODERATE):
        self.level = level
        self._metrics: Dict[str, TpuMetric] = {}

    def metric(self, name: str, level: int = MODERATE) -> TpuMetric:
        if name not in self._metrics:
            self._metrics[name] = TpuMetric(name, level)
        return self._metrics[name]

    def __getitem__(self, name: str) -> TpuMetric:
        return self.metric(name)

    def snapshot(self) -> Dict[str, int]:
        return {m.name: m.value for m in self._metrics.values()
                if m.level <= self.level}
