"""Operator metrics — the GpuMetric analog (GpuExec.scala:49-330).

Levels ESSENTIAL/MODERATE/DEBUG mirror `RapidsConf.scala:674`; standard
names match the reference so dashboards translate: numOutputRows,
numOutputBatches, opTime, semaphoreWaitTime, spillToHostTime, ...

`spark.rapids.sql.metrics.level` is honored at COLLECTION time (the
reference's createMetric gate, GpuExec.scala:229): a registry built at
ESSENTIAL hands back a shared no-op metric for MODERATE/DEBUG
requests, so filtered metrics skip the lock + add entirely instead of
accumulating and being hidden at snapshot.
"""

from __future__ import annotations

import threading
import time
from contextlib import contextmanager
from typing import Dict

ESSENTIAL = 0
MODERATE = 1
DEBUG = 2

_LEVEL_NAMES = {"ESSENTIAL": ESSENTIAL, "MODERATE": MODERATE,
                "DEBUG": DEBUG}

NUM_OUTPUT_ROWS = "numOutputRows"
NUM_OUTPUT_BATCHES = "numOutputBatches"
NUM_INPUT_ROWS = "numInputRows"
NUM_INPUT_BATCHES = "numInputBatches"
OP_TIME = "opTime"
SEMAPHORE_WAIT_TIME = "semaphoreWaitTime"
SPILL_TIME = "spillTime"
BUILD_TIME = "buildTime"
JOIN_TIME = "joinTime"
BLOOM_FILTERED_ROWS = "bloomFilteredRows"
SORT_TIME = "sortTime"
AGG_TIME = "aggTime"
FILTER_TIME = "filterTime"
PARTITION_TIME = "partitionTime"
WINDOW_TIME = "windowTime"
TASK_TIME = "taskTime"


def parse_level(name, default: int = MODERATE) -> int:
    """'ESSENTIAL'|'MODERATE'|'DEBUG' (or an int) -> level constant."""
    if isinstance(name, int):
        return name
    return _LEVEL_NAMES.get(str(name).upper(), default)


def conf_level(conf) -> int:
    """Collection level of a session conf (metrics.level satellite);
    plans built without a conf keep the historical MODERATE."""
    if conf is None:
        return MODERATE
    from spark_rapids_tpu.config import rapids_conf as rc

    return parse_level(conf.get(rc.METRICS_LEVEL))


class TpuMetric:
    __slots__ = ("name", "level", "value", "_lock")

    def __init__(self, name: str, level: int = MODERATE):
        self.name = name
        self.level = level
        self.value = 0
        self._lock = threading.Lock()

    def add(self, v: int):
        with self._lock:
            self.value += int(v)

    @contextmanager
    def ns(self):
        """Nanosecond-scoped timing (GpuExec.scala:134 `ns` helper)."""
        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            self.add(time.monotonic_ns() - t0)


class _NullMetric:
    """Shared sink for metrics above the configured collection level:
    add/ns are no-ops, value pins at 0, and it never lands in a
    registry snapshot."""

    __slots__ = ()
    name = "<filtered>"
    level = DEBUG + 1
    value = 0

    def add(self, v: int):
        pass

    @contextmanager
    def ns(self):
        yield


NULL_METRIC = _NullMetric()


class MetricsRegistry:
    """Per-operator metric set, filtered at the registry's level."""

    def __init__(self, level: int = MODERATE):
        self.level = parse_level(level)
        self._metrics: Dict[str, TpuMetric] = {}

    def metric(self, name: str, level: int = MODERATE):
        if level > self.level:
            return NULL_METRIC
        if name not in self._metrics:
            self._metrics[name] = TpuMetric(name, level)
        return self._metrics[name]

    def __getitem__(self, name: str):
        return self.metric(name)

    def peek(self, name: str) -> int:
        """Current value without registering the metric."""
        m = self._metrics.get(name)
        return m.value if m is not None else 0

    def snapshot(self) -> Dict[str, int]:
        return {m.name: m.value for m in self._metrics.values()
                if m.level <= self.level}
