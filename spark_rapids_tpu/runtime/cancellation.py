"""Cooperative per-query cancellation — the token the governance layer
(runtime/admission.py) threads through execution.

Spark interrupts tasks with Thread.interrupt + TaskContext.isInterrupted
checks; Python threads cannot be interrupted, so the engine uses the
same discipline explicitly: every query owns a `CancelToken`, and the
natural yield points that already exist — scheduler task-attempt
boundaries (runtime/scheduler.py), semaphore waits (runtime/semaphore.py),
backoff sleeps and shuffle fetch/retry loops (runtime/backoff.py,
shuffle/manager.py), the OOM split-and-retry loop (runtime/retry.py),
and the engine-dispatch ladder (api/dataframe.py) — call `check()` or
wait on the token's event. A cancelled or expired query therefore
unwinds within a bounded latency: the longest stretch of work between
two yield points, not "whenever the query happens to finish".

Propagation is thread-local (`scope()`); the stage scheduler captures
the submitting thread's token at `run()` and re-establishes it inside
every pool-thread attempt, the same way it forwards the query id into
the task scope. Blocking waiters (the semaphore) register `on_cancel`
callbacks so a cancel wakes them immediately instead of at the next
poll tick.

The token doubles as the poison-query ledger: worker crashes attributed
to the query land in `record_worker_crash`, and crossing the conf'd
quarantine threshold cancels the token with a QueryQuarantinedError
carrying the crash history.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Callable, List, Optional, Tuple

from spark_rapids_tpu.runtime.errors import (
    QueryCancelledError,
    QueryDeadlineExceeded,
    QueryQuarantinedError,
)


class CancelToken:
    """Per-query cancellation state: a latch + reason + error class,
    an optional absolute deadline, cancel callbacks, and the
    worker-crash history feeding quarantine."""

    def __init__(self, query_id: int, timeout_ms: int = 0,
                 description: str = "",
                 quarantine_threshold: int = 0):
        self.query_id = query_id
        self.description = description
        self.created_at = time.monotonic()
        self.deadline: Optional[float] = (
            self.created_at + timeout_ms / 1000.0 if timeout_ms > 0
            else None)
        self.quarantine_threshold = max(0, int(quarantine_threshold))
        self.cancel_requested_at: Optional[float] = None
        self.crashes: List[Tuple[float, int, int, str]] = []
        self._event = threading.Event()
        self._lock = threading.Lock()
        self._callbacks: List[Callable[[], None]] = []
        self._reason: Optional[str] = None
        self._error_cls = QueryCancelledError
        # cumulative backoff-sleep ledger (runtime/backoff.py): the
        # token is the one per-query object every retry site shares,
        # so the io.retry.maxTotalMs budget accrues here
        self.retry_ms_used = 0.0

    # --- cancellation ---

    def cancel(self, reason: str = "cancelled",
               error_cls: type = QueryCancelledError) -> bool:
        """Latch the token (first cancel wins); fires callbacks outside
        the lock. Returns False when already cancelled."""
        with self._lock:
            if self._event.is_set():
                return False
            self._reason = reason
            self._error_cls = error_cls
            self.cancel_requested_at = time.monotonic()
            self._event.set()
            cbs = list(self._callbacks)
        for cb in cbs:
            try:
                cb()
            except Exception:
                pass  # a waiter's wakeup must never poison the canceller
        return True

    @property
    def cancelled(self) -> bool:
        return self._event.is_set()

    @property
    def expired(self) -> bool:
        return self.deadline is not None and \
            time.monotonic() > self.deadline

    def remaining_s(self) -> Optional[float]:
        """Seconds until the deadline (None = no deadline); bounded
        waiters cap their sleep with this so an expiry wakes them."""
        if self.deadline is None:
            return None
        return max(0.0, self.deadline - time.monotonic())

    def error(self) -> QueryCancelledError:
        reason = self._reason or "cancelled"
        return self._error_cls(
            f"query {self.query_id} {reason}"
            + (f" ({self.description})" if self.description else ""))

    def check(self) -> None:
        """The cooperative yield point: raise when cancelled, and turn
        a passed deadline into a cancel (so every waiter wakes) before
        raising it."""
        if not self._event.is_set() and self.expired:
            elapsed = time.monotonic() - self.created_at
            self.cancel(
                f"deadline exceeded after {elapsed:.1f}s "
                f"(spark.rapids.tpu.query.timeoutMs)",
                QueryDeadlineExceeded)
        if self._event.is_set():
            raise self.error()

    # --- waiter wakeup ---

    def on_cancel(self, cb: Callable[[], None]) -> None:
        """Register a wakeup callback (fires immediately when already
        cancelled) — blocking waiters use this to leave their condition
        variables promptly instead of at a poll tick."""
        with self._lock:
            if not self._event.is_set():
                self._callbacks.append(cb)
                return
        cb()

    def remove_on_cancel(self, cb: Callable[[], None]) -> None:
        with self._lock:
            try:
                self._callbacks.remove(cb)
            except ValueError:
                pass

    def wait(self, timeout_s: float) -> bool:
        """Sleep up to timeout_s, waking early on cancel; True when
        cancelled/deadline-bounded wakeup fired."""
        t = timeout_s
        r = self.remaining_s()
        if r is not None:
            t = min(t, r + 0.001)
        return self._event.wait(max(0.0, t))

    # --- poison-query quarantine feed ---

    def record_worker_crash(self, stage: int, task: int,
                            worker: str) -> None:
        """One scheduler-observed worker crash attributed to this query
        (PR 3's eviction feed). Crossing the quarantine threshold
        cancels the token with the crash history — the query fails fast
        instead of burning stage.maxAttempts per task forever."""
        with self._lock:
            self.crashes.append(
                (time.monotonic() - self.created_at, stage, task, worker))
            n = len(self.crashes)
            history = list(self.crashes)
        if self.quarantine_threshold and \
                n >= self.quarantine_threshold and not self.cancelled:
            rows = ", ".join(
                f"t+{ts:.2f}s stage={st} task={tk} worker={w}"
                for ts, st, tk, w in history)
            self.cancel(
                f"quarantined after {n} worker crashes "
                f"(admission.quarantine.maxWorkerCrashes="
                f"{self.quarantine_threshold}); crash history: [{rows}]",
                QueryQuarantinedError)

    def charge_retry_ms(self, ms: float) -> float:
        """Accrue one backoff delay against this query's cumulative
        retry budget; returns the new total (the caller compares it to
        spark.rapids.tpu.io.retry.maxTotalMs)."""
        with self._lock:
            self.retry_ms_used += ms
            return self.retry_ms_used

    def unwind_latency_s(self) -> Optional[float]:
        """Seconds from cancel request to now — admission.finish reads
        it once the unwind completes (the cancel-latency metric)."""
        if self.cancel_requested_at is None:
            return None
        return time.monotonic() - self.cancel_requested_at


# ------------------------------------------------- thread-local scope

_tls = threading.local()


def current() -> Optional[CancelToken]:
    return getattr(_tls, "token", None)


@contextlib.contextmanager
def scope(token: Optional[CancelToken]):
    """Establish `token` as the thread's query token; nests (an inner
    scope restores the outer on exit). A None token clears the scope —
    useful for background work that must not inherit a query's fate."""
    prev = getattr(_tls, "token", None)
    _tls.token = token
    try:
        yield token
    finally:
        _tls.token = prev


def check_current() -> None:
    """Module-level yield point: no-op without a token in scope."""
    t = getattr(_tls, "token", None)
    if t is not None:
        t.check()


def sleep_interruptible(delay_s: float) -> None:
    """time.sleep that a cancel (or deadline) cuts short — the backoff
    loops' sleep primitive, so a cancelled query never rides out a
    2-second retry delay before noticing."""
    t = getattr(_tls, "token", None)
    if t is None:
        time.sleep(delay_s)
        return
    t.check()
    t.wait(delay_s)
    t.check()
