"""Persistent cross-process compilation layer.

The structural jit cache (runtime/jit_cache.py) evaporates with the
process, so a fresh session pays full XLA compilation for every fused
program variant — BENCH round 5 measured 482 s of cold start against a
7.7 s CPU cold read, almost all of it compilation of the multiplied
fused-program variants. The reference pays no such tax (cuDF kernels
are precompiled); Theseus (arxiv 2508.05029) and the Presto-on-GPU
work treat time-to-first-query as a first-class engine metric. This
module is the XLA-native answer, three layers deep:

1. DISK-BACKED PROGRAM CACHE — JAX's persistent compilation cache is
   pointed at a versioned engine directory, so any process re-tracing
   a structurally identical program loads the serialized XLA
   executable instead of recompiling (tracing is host seconds;
   compilation was the minutes). Entry keys are XLA's own
   (HLO + compile options + jaxlib build), so cross-version collisions
   are impossible by construction.

2. KEY -> ARTIFACT INDEX — our own index over the structural keys
   (Expression.key() trees + schema + _env_token()): per-program hit
   counts, compile seconds, and (for fused whole-stage programs) a
   serialized `jax.export` artifact. The index is stamped with the
   jax/jaxlib/plugin/backend version tuple and WIPED on any mismatch
   (stale-artifact invalidation); every write is
   write-temp-then-rename so concurrent sessions never observe torn
   entries, and artifacts carry the full key repr so a digest
   collision is detected at load instead of serving a wrong program.

3. ASYNC WARMUP — a conf-gated background thread AOT-compiles the
   top-K most-used artifacts from prior runs while the first scan's
   decode/upload I/O is in flight; `cached_jit` then serves the
   ready executable, skipping even re-tracing for the hot programs.

Observability rides along: a process-wide `CompileStats` ledger
(programs compiled / cache hits / warm hits / compile seconds) that
per-query metrics snapshot (api/dataframe.py, session.last_execution),
so the bench and CI can watch cold start forever.
"""

from __future__ import annotations

import hashlib
import json
import os
import pickle
import queue
import shutil
import tempfile
import threading
from typing import Any, Callable, Dict, Optional, Tuple

# tags of cached_jit keys whose programs are worth exporting to disk
# artifacts for cross-process warmup: the fused whole-stage programs
# (the cold-start dominators). Eager per-operator programs recompile in
# milliseconds-to-seconds via layer 1 and are not worth the artifact.
_ARTIFACT_TAGS = ("fused",)


class CompileStats:
    """Process-wide compilation ledger; snapshot deltas become the
    per-query compile metrics."""

    def __init__(self):
        self._lock = threading.Lock()
        self.programs_compiled = 0     # fresh jit builds this process
        self.cache_hits = 0            # in-memory structural reuse
        self.warm_hits = 0             # artifact-served programs
        self.compile_seconds = 0.0     # trace+compile time of builds
        self.artifacts_quarantined = 0  # corrupt entries set aside

    @staticmethod
    def _emit(kind: str, **fields) -> None:
        from spark_rapids_tpu.obs import events as obs_events

        obs_events.emit("compile", kind=kind, **fields)

    def on_compile(self, seconds: float) -> None:
        with self._lock:
            self.programs_compiled += 1
            self.compile_seconds += float(seconds)
        self._emit("miss", seconds=round(float(seconds), 4))

    def on_hit(self) -> None:
        with self._lock:
            self.cache_hits += 1
        self._emit("hit")

    def on_warm_hit(self) -> None:
        with self._lock:
            self.warm_hits += 1
        self._emit("warm")

    def on_quarantine(self) -> None:
        with self._lock:
            self.artifacts_quarantined += 1
        self._emit("quarantine")

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "programsCompiled": self.programs_compiled,
                "cacheHits": self.cache_hits,
                "warmHits": self.warm_hits,
                "compileSeconds": round(self.compile_seconds, 3),
                "artifactsQuarantined": self.artifacts_quarantined,
            }

    @staticmethod
    def delta(before: Dict[str, Any], after: Dict[str, Any]
              ) -> Dict[str, Any]:
        return {k: (round(after[k] - before[k], 3)
                    if isinstance(after[k], float)
                    else after[k] - before[k])
                for k in after}


stats = CompileStats()

_lock = threading.Lock()
_configured_dir: Optional[str] = None   # None = disabled
_artifact_min_s = 0.5   # export threshold; set from conf at configure
_saver: Optional["_AsyncSaver"] = None
_warm: Dict[str, Callable] = {}         # key repr -> ready executable
_warm_lock = threading.Lock()
_warmup_thread: Optional[threading.Thread] = None
_warmed_dir: Optional[str] = None   # warmup ran for this dir already
_export_serialization_ready = False


def version_token() -> Dict[str, str]:
    """Everything that invalidates serialized artifacts: jax traces
    differently across versions, jaxlib executables are ABI-bound, the
    plugin's lowerings change per release, and a backend switch changes
    every program."""
    import jax
    import jaxlib

    import spark_rapids_tpu

    return {
        "jax": jax.__version__,
        "jaxlib": jaxlib.__version__,
        "plugin": getattr(spark_rapids_tpu, "__version__", "0"),
        "backend": jax.default_backend(),
    }


def key_digest(full_key: Tuple) -> str:
    """Stable cross-process digest of a structural key. Structural keys
    are built from strs/ints/bools/bytes and dtype reprs (the
    Expression.key() audit), so repr() is process-stable."""
    return hashlib.sha256(repr(full_key).encode()).hexdigest()[:32]


def default_dir() -> str:
    return os.path.join(tempfile.gettempdir(), "srtpu_compile_cache")


def enabled() -> bool:
    return _configured_dir is not None


def cache_dir() -> Optional[str]:
    return _configured_dir


def _index_dir() -> str:
    return os.path.join(_configured_dir, "index")


def _artifact_dir() -> str:
    return os.path.join(_configured_dir, "artifacts")


def _atomic_write(path: str, data: bytes) -> None:
    """Concurrent-writer discipline: temp file in the same directory +
    rename, so readers never see a torn entry and the last writer
    wins."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path),
                               prefix=".tmp-")
    try:
        with os.fdopen(fd, "wb") as f:
            f.write(data)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def _check_version_stamp(root: str) -> None:
    """Wipe index + artifacts + XLA entries on any version-tuple
    mismatch; stamp the current tuple. A second process racing the wipe
    at worst re-wipes — entries are re-creatable by definition."""
    stamp = os.path.join(root, "VERSION.json")
    tok = version_token()
    try:
        with open(stamp) as f:
            if json.load(f) == tok:
                return
    except (OSError, ValueError):
        pass
    for sub in ("index", "artifacts", "xla"):
        shutil.rmtree(os.path.join(root, sub), ignore_errors=True)
    _atomic_write(stamp, json.dumps(tok).encode())


def configure(conf=None) -> None:
    """Session-lifecycle hook (plugin.py TpuExecutorPlugin.init): enable
    the persistent layers per conf. Idempotent for a repeated dir."""
    global _configured_dir, _saver, _artifact_min_s
    from spark_rapids_tpu.config import rapids_conf as rc

    if conf is not None:
        _artifact_min_s = conf.get(rc.COMPILE_CACHE_ARTIFACT_MIN_S)
    if conf is not None and not conf.get(rc.COMPILE_CACHE_ENABLED):
        with _lock:
            if _configured_dir is not None:
                import jax

                jax.config.update("jax_compilation_cache_dir", None)
            _configured_dir = None
        return
    root = (conf.get(rc.COMPILE_CACHE_DIR) if conf is not None
            else "") or default_dir()
    root = os.path.abspath(root)
    with _lock:
        already = _configured_dir == root
        if not already:
            os.makedirs(root, exist_ok=True)
            _check_version_stamp(root)
            for sub in ("index", "artifacts", "xla"):
                os.makedirs(os.path.join(root, sub), exist_ok=True)
            _enable_jax_persistent_cache(os.path.join(root, "xla"))
            _configured_dir = root
        if _saver is None:
            _saver = _AsyncSaver()
    if conf is not None and conf.get(rc.COMPILE_CACHE_WARMUP):
        start_warmup(conf.get(rc.COMPILE_CACHE_WARMUP_TOP_K))


def _enable_jax_persistent_cache(xla_dir: str) -> None:
    """Layer 1: every XLA compile (eager operators included) round-trips
    through jax's disk cache. min thresholds drop to zero — cold start
    is the SUM of many sub-second compiles, so the defaults' 1 s floor
    would leave most of the tax in place."""
    import jax

    jax.config.update("jax_compilation_cache_dir", xla_dir)
    for k, v in (("jax_persistent_cache_min_compile_time_secs", 0.0),
                 ("jax_persistent_cache_min_entry_size_bytes", -1)):
        try:
            jax.config.update(k, v)
        except (AttributeError, ValueError):  # older jax: keep floors
            pass


# ------------------------------------------------------------- index

def _index_path(digest: str) -> str:
    return os.path.join(_index_dir(), digest + ".json")


def read_index() -> Dict[str, Dict[str, Any]]:
    """digest -> entry; skips torn/foreign files defensively."""
    out: Dict[str, Dict[str, Any]] = {}
    if not enabled():
        return out
    try:
        names = os.listdir(_index_dir())
    except OSError:
        return out
    for name in names:
        if not name.endswith(".json"):
            continue
        try:
            with open(os.path.join(_index_dir(), name)) as f:
                out[name[:-5]] = json.load(f)
        except (OSError, ValueError):
            continue
    return out


def _record_index(digest: str, key_repr: str, tag: str,
                  seconds: float, has_artifact: bool) -> None:
    path = _index_path(digest)
    entry = {"key": key_repr, "tag": tag, "count": 0,
             "compile_s": 0.0, "artifact": has_artifact}
    try:
        with open(path) as f:
            prev = json.load(f)
        if prev.get("key") == key_repr:
            entry = prev
            entry["artifact"] = entry.get("artifact", False) or \
                has_artifact
    except (OSError, ValueError):
        pass
    entry["count"] = int(entry.get("count", 0)) + 1
    entry["compile_s"] = round(
        float(entry.get("compile_s", 0.0)) + seconds, 4)
    _atomic_write(path, json.dumps(entry).encode())


# --------------------------------------------------------- artifacts

def _register_export_serialization() -> None:
    """jax.export must be taught the engine's pytree containers once per
    process; aux data (schemas, dtypes, vranges) pickles."""
    global _export_serialization_ready
    if _export_serialization_ready:
        return
    import jax.export as jex

    from spark_rapids_tpu.columnar.batch import ColumnBatch, DeviceColumn
    from spark_rapids_tpu.ops.joinops import BuildTable

    for node in (DeviceColumn, ColumnBatch):
        try:
            jex.register_pytree_node_serialization(
                node,
                serialized_name=f"srtpu.{node.__name__}",
                serialize_auxdata=pickle.dumps,
                deserialize_auxdata=pickle.loads)
        except ValueError:
            pass  # already registered (session re-init)
    try:
        jex.register_namedtuple_serialization(
            BuildTable, serialized_name="srtpu.BuildTable")
    except ValueError:
        pass
    _export_serialization_ready = True


class _AsyncSaver(threading.Thread):
    """Write-behind index/artifact persistence: exporting a fused
    program re-traces it (host seconds), which must not sit on the
    query's critical path. Bounded queue; overflow drops the artifact,
    never blocks the query."""

    def __init__(self):
        super().__init__(name="srtpu-compile-cache-saver", daemon=True)
        self.q: "queue.Queue" = queue.Queue(maxsize=256)
        self.start()

    def run(self):
        while True:
            item = self.q.get()
            if item is None:
                self.q.task_done()
                return
            try:
                self._save(*item)
            except Exception:
                pass  # artifacts are best-effort by contract
            finally:
                self.q.task_done()

    def _save(self, full_key, tag, seconds, jitted, avals):
        digest = key_digest(full_key)
        key_repr = repr(full_key)
        has_artifact = False
        if (jitted is not None and avals is not None
                and tag in _ARTIFACT_TAGS):
            has_artifact = self._export(digest, key_repr, jitted, avals)
        _record_index(digest, key_repr, tag, seconds, has_artifact)

    def _export(self, digest, key_repr, jitted, avals) -> bool:
        try:
            import jax.export as jex

            _register_export_serialization()
            exp = jex.export(jitted)(*avals)
            blob = exp.serialize()
        except Exception:
            return False  # program outside export's subset: index-only
        _atomic_write(os.path.join(_artifact_dir(), digest + ".key"),
                      key_repr.encode())
        _atomic_write(os.path.join(_artifact_dir(), digest + ".bin"),
                      blob)
        return True


def record_use(full_key: Tuple, tag: str) -> None:
    """Bump a program's index count WITHOUT a compile (warm-served or
    cross-query reuse): top-K warmup ranks by count, so programs every
    process touches must outrank one-off entries from past runs."""
    if not enabled() or _saver is None:
        return
    try:
        _saver.q.put_nowait((full_key, tag, 0.0, None, None))
    except queue.Full:
        pass


def record_build(full_key: Tuple, tag: str, seconds: float,
                 jitted=None, args: Optional[tuple] = None) -> None:
    """Called by cached_jit after a fresh build's first dispatch:
    account the compile and enqueue persistence. Input AVALS are
    captured here (cheap, host-side) instead of the arrays — holding
    example batches until the saver runs would pin gigabytes of HBM."""
    stats.on_compile(seconds)
    if not enabled() or _saver is None:
        return
    avals = None
    if (args is not None and tag in _ARTIFACT_TAGS
            and seconds >= _artifact_min_s):
        try:
            import jax

            avals = jax.tree_util.tree_map(
                lambda leaf: jax.ShapeDtypeStruct(leaf.shape, leaf.dtype),
                args)
        except Exception:
            avals = None
    try:
        _saver.q.put_nowait((full_key, tag, seconds, jitted, avals))
    except queue.Full:
        pass


def flush(timeout: float = 30.0) -> None:
    """Drain pending index/artifact writes (tests, session stop)."""
    if _saver is not None:
        try:
            _saver.q.join()
        except Exception:
            pass


# ------------------------------------------------------------ warmup

def take_warm(full_key: Tuple) -> Optional[Callable]:
    """Ready executable for a structural key, if warmup loaded one.
    Matched on the FULL key repr (not the digest), so a digest
    collision can never serve the wrong program."""
    if not _warm:
        return None
    with _warm_lock:
        return _warm.pop(repr(full_key), None)


def warm_count() -> int:
    with _warm_lock:
        return len(_warm)


def invalidate_warm() -> int:
    """Device-loss recovery hook (runtime/device_monitor.py): warm AOT
    executables were loaded against the PJRT client the recovery just
    tore down — drop them all. The disk artifacts they came from stay
    valid (serialized HLO, epoch-free keys) and re-serve lazily: a
    later session init re-runs warmup against the fresh backend, and a
    cache miss simply recompiles. Returns how many were dropped."""
    global _warmed_dir
    with _warm_lock:
        n = len(_warm)
        _warm.clear()
    with _lock:
        # let the next configure() warm up again for the same dir
        _warmed_dir = None
    return n


def start_warmup(top_k: int = 32) -> None:
    """Layer 3: AOT-compile the top-K most-used prior-run artifacts in
    the background (overlapping the first scan's decode/upload I/O).
    Each compile also primes jax's persistent-cache memory layer, so
    even a program the warm table misses gets its disk entry hot."""
    global _warmup_thread, _warmed_dir
    if not enabled():
        return
    with _lock:
        # once per process per cache dir: session churn (tests, REPL
        # re-creation) must not re-scan the index every init
        if _warmed_dir == _configured_dir:
            return
        if _warmup_thread is not None and _warmup_thread.is_alive():
            return
        _warmed_dir = _configured_dir
        _warmup_thread = threading.Thread(
            target=_warmup_run, args=(int(top_k),),
            name="srtpu-compile-cache-warmup", daemon=True)
        _warmup_thread.start()


def warmup_join(timeout: Optional[float] = None) -> None:
    t = _warmup_thread
    if t is not None:
        t.join(timeout)


def _warmup_run(top_k: int) -> None:
    entries = [(d, e) for d, e in read_index().items()
               if e.get("artifact")]
    entries.sort(key=lambda de: (-int(de[1].get("count", 0)), de[0]))
    for digest, entry in entries[:top_k]:
        try:
            fn = _load_artifact(digest, entry["key"])
        except Exception:
            fn = None
        if fn is not None:
            with _warm_lock:
                _warm[entry["key"]] = fn


def quarantine_artifact(digest: str) -> None:
    """Set a corrupt artifact's files aside (rename to .quarantine) so
    the next run neither re-reads the poison nor loses the evidence;
    count it so metrics surface decay of the cache medium."""
    adir = _artifact_dir()
    for ext in (".bin", ".key"):
        src = os.path.join(adir, digest + ext)
        try:
            os.replace(src, src + ".quarantine")
        except OSError:
            pass
    stats.on_quarantine()


def _load_artifact(digest: str, key_repr: str) -> Optional[Callable]:
    """Deserialize + AOT-compile one artifact. The .key sidecar must
    equal the index's key repr — a mismatch means a digest collision or
    a torn write, and the artifact is ignored.

    Failure contract (PR 2): a corrupt/truncated artifact — or an
    injected compile.cache_load fault — is a CACHE MISS, never a query
    failure: the file is quarantined, a metric counts it, and the
    program recompiles from source as if the entry never existed."""
    import jax

    from spark_rapids_tpu.runtime import faults

    adir = _artifact_dir()
    try:
        faults.maybe_inject("compile.cache_load", detail=digest)
        with open(os.path.join(adir, digest + ".key"), "rb") as f:
            if f.read().decode() != key_repr:
                return None
        with open(os.path.join(adir, digest + ".bin"), "rb") as f:
            blob = f.read()
        import jax.export as jex

        _register_export_serialization()
        exp = jex.deserialize(blob)
        args, kwargs = jax.tree_util.tree_unflatten(
            exp.in_tree, exp.in_avals)
        return jax.jit(exp.call).lower(*args, **kwargs).compile()
    except FileNotFoundError:
        return None  # plain miss: nothing to quarantine
    except Exception:
        quarantine_artifact(digest)
        return None


# ------------------------------------------------------------- admin

def clear(remove_files: bool = False) -> None:
    """Test hook: drop warm table (+ optionally the on-disk entries)."""
    global _warmup_thread, _warmed_dir
    with _warm_lock:
        _warm.clear()
    _warmup_thread = None
    _warmed_dir = None
    if remove_files and enabled():
        for sub in ("index", "artifacts"):
            d = os.path.join(_configured_dir, sub)
            shutil.rmtree(d, ignore_errors=True)
            os.makedirs(d, exist_ok=True)


def reset_for_tests() -> None:
    """Full deconfigure (tests only): subsequent sessions reconfigure."""
    global _configured_dir, _saver, _warmup_thread, _warmed_dir
    flush()
    with _lock:
        _configured_dir = None
        _saver = None
    with _warm_lock:
        _warm.clear()
    _warmup_thread = None
    _warmed_dir = None
