"""Engine degradation ladder state — circuit breaker + demotion ledger.

The engine dispatch (api/dataframe.py `_dispatch_engines`) runs a
query on the fastest engine that can take it: fused -> eager
out-of-core -> CPU. PR 2 turns that chain into an explicit DEGRADATION
LADDER for execution FAILURES, not just missing lowerings: a fused run
that dies with a terminal OOM or an injected device.dispatch fault
demotes to the eager engine (where the OOM retry/split machinery
lives), and an eager failure demotes to the CPU engine — every
demotion recorded in `last_execution["degradations"]` and the
`degrade.*` session metrics, the way memory-oversubscription systems
(Vortex, PAPERS.md) treat pressure as a normal signal to degrade
around rather than a crash.

This module holds the cross-query state: a PER-PROGRAM-KEY circuit
breaker. A plan whose fused execution keeps failing (same structural
key) stops being retried on the fused engine after
`spark.rapids.tpu.degrade.circuitBreaker.threshold` consecutive
failures — later queries skip straight to eager instead of paying the
doomed compile+run, until one success (e.g. after a conf change or
smaller input) closes the breaker again.
"""

from __future__ import annotations

import threading
from typing import Dict, Tuple

_DEFAULT_THRESHOLD = 3


class CircuitBreaker:
    """Consecutive-failure breaker keyed on structural program keys."""

    def __init__(self, threshold: int = _DEFAULT_THRESHOLD):
        self.threshold = max(1, int(threshold))
        self._failures: Dict[Tuple, int] = {}
        self._lock = threading.Lock()
        self.opens = 0  # times a key crossed the threshold

    def allow(self, key: Tuple) -> bool:
        with self._lock:
            return self._failures.get(key, 0) < self.threshold

    def record_failure(self, key: Tuple) -> int:
        with self._lock:
            n = self._failures.get(key, 0) + 1
            self._failures[key] = n
            if n == self.threshold:
                self.opens += 1
            return n

    def record_success(self, key: Tuple) -> None:
        with self._lock:
            self._failures.pop(key, None)

    def open_keys(self) -> int:
        with self._lock:
            return sum(1 for n in self._failures.values()
                       if n >= self.threshold)


_breaker = CircuitBreaker()
_counters: Dict[str, int] = {}
_lock = threading.Lock()


def configure(conf=None) -> None:
    """Session hook: re-thresholds the breaker (state survives —
    a failing program stays known across sessions in one process)."""
    from spark_rapids_tpu.config import rapids_conf as rc

    if conf is not None:
        _breaker.threshold = max(1, conf.get(rc.DEGRADE_CB_THRESHOLD))


def breaker() -> CircuitBreaker:
    return _breaker


def enabled(conf=None) -> bool:
    from spark_rapids_tpu.config import rapids_conf as rc

    return conf is None or bool(conf.get(rc.DEGRADE_ENABLED))


def plan_fingerprint(phys) -> Tuple:
    """Structural key of a physical plan — the breaker's unit of
    memory. Reuses the mesh/fused program-key discipline so two plans
    that would trace identical programs share breaker state."""
    from spark_rapids_tpu.parallel.plan_compiler import _plan_key

    return ("degrade", _plan_key(phys))


def record_demotion(kind: str, frm: str = None, to: str = None,
                    reason: str = None) -> None:
    """Process-wide demotion counter ('fusedToEager', 'eagerToCpu',
    'breakerShortCircuit', 'fusedOomInjectionFallback'); every
    demotion also lands on the obs bus (with from/to/reason when the
    dispatch site supplies them) for the event log and reports."""
    with _lock:
        _counters[kind] = _counters.get(kind, 0) + 1
    from spark_rapids_tpu.obs import events as obs_events

    fields = {"kind": kind}
    if frm is not None:
        fields["from"] = frm
    if to is not None:
        fields["to"] = to
    if reason is not None:
        fields["reason"] = reason
    obs_events.emit("degrade", **fields)


def counters() -> Dict[str, int]:
    with _lock:
        out = dict(_counters)
    out["breakerOpens"] = _breaker.opens
    out["breakerOpenKeys"] = _breaker.open_keys()
    return out


def reset_for_tests(threshold: int = _DEFAULT_THRESHOLD) -> None:
    global _breaker
    _breaker = CircuitBreaker(threshold)
    with _lock:
        _counters.clear()
