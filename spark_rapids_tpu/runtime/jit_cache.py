"""Global compiled-program cache keyed on program STRUCTURE.

`jax.jit` caches compiled executables per function *object*. Operators
used to call `jax.jit(self._run)` in __init__, so every new query plan
(fresh operator instances) recompiled structurally identical programs —
tens of seconds per query on TPU. The reference has no analog problem
(cuDF kernels are precompiled); the XLA-native answer is to key the
jitted callable on the structural description of the program
(Expression.key() trees + output schema) so any query with the same
shape of work reuses the compiled artifact, exactly like a second batch
through the same operator does.

Entries hold the first instance's bound method; behavior must be fully
determined by the key (expression keys include dtypes/ordinals/params,
schema keys include names) — the audit lives in the expr key() overrides.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, Tuple

import jax

_cache: Dict[Tuple, Callable] = {}
_lock = threading.Lock()


_segmented_mod = None


def _env_token() -> Tuple:
    """Trace-environment facts that change what a structurally identical
    program computes: the backend (kernels branch on it, e.g. the MXU
    segmented reductions) and the test-only forced-matmul flag.
    Deliberately EPOCH-FREE: this token rides into the persistent
    compile-cache keys, and disk artifacts survive a device-loss
    recovery (they reload into the rebuilt client) as well as process
    restarts that reset the epoch to 1."""
    global _segmented_mod
    if _segmented_mod is None:  # lazy: segmented imports columnar.batch
        from spark_rapids_tpu.ops import segmented

        _segmented_mod = segmented
    return (jax.default_backend(), _segmented_mod._MM_FORCE.get())


_device_monitor_mod = None


def _mem_key(full: Tuple) -> Tuple:
    """In-memory cache key: the persistent key PLUS the device epoch
    (runtime/device_monitor.py). Executables jitted against a backend
    that device-loss recovery tore down must never be re-dispatched —
    the epoch bump makes every pre-recovery entry a miss, and programs
    re-intern lazily against the fresh client (via the epoch-free disk
    artifacts when one exists)."""
    global _device_monitor_mod
    if _device_monitor_mod is None:  # lazy: avoids an import cycle
        from spark_rapids_tpu.runtime import device_monitor

        _device_monitor_mod = device_monitor
    return full + (("deviceEpoch", _device_monitor_mod._EPOCH),)


def cached_jit(key: Tuple, build: Callable[[], Callable],
               **jit_kwargs) -> Callable:
    """Return a callable dispatching to the jitted program for `key`,
    building it on first use. The trace-environment part of the key is
    resolved at CALL time, not construction time — jax.jit traces
    lazily on first call, so a construction-time snapshot could label a
    trace with an environment it was not traced under.

    Entries route through the persistent compilation layer
    (runtime/compile_cache.py): a fresh build's first dispatch is timed
    and recorded (and, for fused whole-stage programs, exported to a
    disk artifact), and a key the background warmup already AOT-compiled
    is served without building — the cross-process analog of this
    module's in-process structural reuse."""

    def dispatch(*args, **kwargs):
        mem = _mem_key(key + _env_token())
        # lock-free fast path: CPython dict reads are atomic, and every
        # per-batch dispatch engine-wide funnels through here
        fn = _cache.get(mem)
        if fn is None:
            with _lock:
                fn = _cache.get(mem)
                if fn is None:
                    fn = _make_entry(mem[:-1], key, build, jit_kwargs)
                    _cache[mem] = fn
        return fn(*args, **kwargs)

    return dispatch


def _make_entry(full: Tuple, key: Tuple, build: Callable[[], Callable],
                jit_kwargs) -> Callable:
    """One cache entry: either a warmup-served AOT executable (with a
    build-on-mismatch fallback) or a jax.jit whose first dispatch is
    timed for the compile ledger. Must be called under _lock."""
    from spark_rapids_tpu.runtime import compile_cache as cc

    tag = key[0] if key and isinstance(key[0], str) else "?"
    warm = cc.take_warm(full) if not jit_kwargs else None
    state = {"jitted": None, "timed": warm is not None}
    entry_lock = threading.Lock()

    def entry(*args, **kwargs):
        if warm is not None and state["jitted"] is None:
            try:
                return warm(*args, **kwargs)
            except Exception:
                # aval/env drift between the recording and this
                # process: rebuild live, never fail the query
                pass
        fn = state["jitted"]
        if fn is not None and state["timed"]:
            return fn(*args, **kwargs)
        with entry_lock:
            if state["jitted"] is None:
                state["jitted"] = jax.jit(build(), **jit_kwargs)
            if not state["timed"]:
                state["timed"] = True
                t0 = time.perf_counter()
                out = state["jitted"](*args, **kwargs)
                # async dispatch returns once tracing+compilation are
                # done (execution overlaps) — the cold-start quantity
                cc.record_build(
                    full, tag, time.perf_counter() - t0,
                    state["jitted"],
                    args if not (kwargs or jit_kwargs) else None)
                return out
        return state["jitted"](*args, **kwargs)

    if warm is not None:
        cc.stats.on_warm_hit()
        cc.record_use(full, tag)
    return entry


def detached(op):
    """Shallow copy of an operator with children (and conf) stripped, so
    a cached bound method does not pin the whole physical plan — and
    through it source tables — for the process lifetime. Phase functions
    (_run/_partial/...) only read the operator's own expression fields."""
    import copy

    c = copy.copy(op)
    c.children = []
    c.conf = None
    return c


def probe(key: Tuple) -> bool:
    """Whether a program for `key` (under the CURRENT trace
    environment and device epoch) is already resident — per-query
    compiled-vs-hit accounting without forcing a build."""
    return _mem_key(key + _env_token()) in _cache


def cache_size() -> int:
    with _lock:
        return len(_cache)


def clear():
    with _lock:
        _cache.clear()


def schema_key(schema) -> Tuple:
    return tuple((f.name, repr(f.dataType), f.nullable)
                 for f in schema.fields)


def aliases_key(aliases) -> Tuple:
    return tuple((a.name, a.key()) for a in aliases)


def orders_key(orders) -> Tuple:
    return tuple((o.expr.key(), o.ascending, o.nulls_first)
                 for o in orders)
