"""Device-loss resilience: fatal-TPU detection, fencing, warm recovery.

The reference plugin treats a fatal CUDA error as process-fatal
(Plugin.scala:651-675 exits so the cluster manager reschedules); a
long-running accelerated service cannot — a PJRT client crash or a
wedged TPU runtime must cost one recovery window, not the warm engine,
its compile cache, and every tenant's session ("Accelerating Presto
with GPUs", PAPERS.md). This module is the recovery subsystem:

- **Classification** (`classify`): every dispatch/transfer site routes
  device errors through `guard(site)`, which sorts them into
  `fatal` (XLA INTERNAL / device-lost / wedged-runtime markers, plus
  the `device.fatal` chaos site), `oom` (TpuOOMError — stays with the
  PR 5 TpuRetryOOM retry path, untouched here), and `other`
  (transient/logic errors, surfaced unchanged to their own recovery).
- **Fencing**: the first fatal observation flips the engine FENCED —
  new admissions queue, shed, or degrade to the CPU rung per
  `spark.rapids.tpu.device.recovery.fencedAdmission`, and every
  in-flight query is cancelled with a retryable `DeviceLostError`
  carrying the epoch (PR 7's sanitizer edges and the semaphore drain
  through the normal cancel unwind).
- **Device epoch**: a process-wide counter stamped on every
  `DeviceColumn` (columnar/batch.py) and spill-catalog device
  reservation (runtime/memory.py) and folded into the jit-cache trace
  environment (runtime/jit_cache.py). A stale handle raises
  `DeviceLostError` at use instead of touching a dead buffer; the
  epoch bumps EXACTLY once per fence.
- **Warm recovery** (background thread): wait for the fenced queries
  to drain, bump the epoch, rebuild the PJRT backend
  (`jax.extend.backend.clear_backends`), drop the DEVICE spill tier
  (host/disk tiers survive and unspill into the new epoch on next
  use; device-only state is recomputed by the lineage scheduler /
  query resubmission), invalidate the encoded-dictionary device cache
  (columnar/encoding.py) and PR 1's warm AOT executables (re-served
  lazily from disk artifacts), mark the HBM timeline, then unfence.
- **Resubmission**: the outermost collect (api/dataframe.py) catches
  `DeviceLostError`, waits for the fence to lift (`await_ready`), and
  resubmits once through admission — the retryVictim pattern.

Everything is observable: `device.fatal` / `device.fence` /
`device.recovery` events (epoch-tagged) plus DeviceFence/
DeviceRecovery operator spans, and the `device` block in
`session.robustness_metrics`.
"""

from __future__ import annotations

import contextlib
import threading
import time
from typing import Dict, Optional

from spark_rapids_tpu.runtime.errors import DeviceLostError, TpuOOMError
from spark_rapids_tpu.runtime.faults import InjectedFault

#: message markers of an unrecoverable runtime failure inside an
#: XlaRuntimeError (the CudaFatalException analog for PJRT): the device
#: or its client is gone, not one allocation or one program
_FATAL_MARKERS = (
    "INTERNAL:", "device lost", "DEVICE_LOST", "hardware", "halted",
    "device or resource busy", "Failed to connect", "client is dead",
    "backend is gone",
)

#: process-wide device epoch; read directly (plain int load) by the
#: DeviceColumn constructor and the jit-cache env token — bumped only
#: by the monitor under its lock, exactly once per fence
_EPOCH = 1


def current_epoch() -> int:
    return _EPOCH


def classify(exc: BaseException) -> str:
    """'fatal' | 'oom' | 'other'. Conservative on purpose: OOMs stay
    with the TpuRetryOOM retry/split machinery, transient XLA noise
    stays with backoff — only a dead device/runtime is fatal."""
    if isinstance(exc, DeviceLostError):
        return "fatal"  # already classified (stale-handle raise)
    if isinstance(exc, InjectedFault):
        return "fatal" if exc.site == "device.fatal" else "other"
    if isinstance(exc, TpuOOMError):
        return "oom"
    if type(exc).__name__ == "XlaRuntimeError":
        msg = str(exc)
        if "RESOURCE_EXHAUSTED" in msg:
            return "oom"
        if any(m in msg for m in _FATAL_MARKERS):
            return "fatal"
    return "other"


class DeviceMonitor:
    """Process-wide fence/epoch/recovery state machine."""

    def __init__(self, enabled: bool = True,
                 fenced_admission: str = "degrade",
                 resubmit: bool = True,
                 drain_timeout_ms: int = 30_000,
                 recovery_timeout_ms: int = 60_000,
                 rebuild_backend: bool = True):
        self.enabled = enabled
        self.fenced_admission = fenced_admission
        self.resubmit = resubmit
        self.drain_timeout_ms = max(0, int(drain_timeout_ms))
        self.recovery_timeout_ms = max(1, int(recovery_timeout_ms))
        self.rebuild_backend = rebuild_backend
        self._cv = threading.Condition()
        self._fenced = False
        self._fence_cause = ""
        self._stats: Dict[str, int] = {
            "fatalErrors": 0, "fences": 0, "recoveries": 0,
            "staleHandles": 0, "drainTimeouts": 0,
            "buffersDropped": 0, "buffersRestorable": 0,
            "resubmits": 0, "chipFences": 0, "chipRecoveries": 0,
            "hostFences": 0, "hostRecoveries": 0,
        }
        self.last_recovery_ms = 0.0

    # --- read surface ---

    @property
    def fenced(self) -> bool:
        return self._fenced

    @property
    def epoch(self) -> int:
        return _EPOCH

    def counters(self) -> Dict[str, int]:
        with self._cv:
            out = dict(self._stats)
        out["epoch"] = _EPOCH
        out["fenced"] = int(self._fenced)
        out["lastRecoveryMs"] = round(self.last_recovery_ms, 3)
        out["fencedChips"] = len(_fenced_chips)
        out["chipEpoch"] = _chip_epoch
        out["fencedHosts"] = len(_fenced_hosts)
        return out

    def note_stale_handle(self) -> None:
        with self._cv:
            self._stats["staleHandles"] += 1

    def note_resubmit(self) -> None:
        with self._cv:
            self._stats["resubmits"] += 1

    # --- fatal observation / fence ---

    def report_fatal(self, exc: BaseException, site: str
                     ) -> DeviceLostError:
        """One fatal device error observed at `site`. The FIRST
        observer fences the engine, cancels every running query with a
        retryable DeviceLostError, and starts the recovery thread;
        concurrent observers just get their error. Returns the
        DeviceLostError the caller must raise — the observer unwinds
        like any cancelled query, releasing its permits and buffers
        before recovery touches the backend."""
        from spark_rapids_tpu.obs import events as obs_events

        observed = _EPOCH
        err = DeviceLostError(
            f"device lost at {site} (epoch {observed}): "
            f"{type(exc).__name__}: {exc}", epoch=observed)
        if not self.enabled:
            return err
        with self._cv:
            self._stats["fatalErrors"] += 1
            first = not self._fenced
            if first:
                self._fenced = True
                self._fence_cause = f"{site}: {type(exc).__name__}"
                self._stats["fences"] += 1
        obs_events.emit("device.fatal", site=site, epoch=observed,
                        error=f"{type(exc).__name__}: {exc}")
        if first:
            self._fence(observed, site)
        return err

    def _fence(self, observed: int, site: str) -> None:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import admission

        ctrl = admission.get()
        in_flight = ctrl.cancel_running(
            f"device lost at {site} (epoch {observed}); "
            f"fencing for warm recovery",
            error_cls=DeviceLostError)
        obs_events.emit("device.fence", epoch=observed, cause=site,
                        inFlight=in_flight)
        t = threading.Thread(target=self._recover,
                             args=(time.monotonic(),),
                             name="srtpu-device-recovery", daemon=True)
        t.start()

    # --- warm recovery (background) ---

    def _await_drain(self) -> bool:
        """Wait (bounded) for the fenced queries to unwind: no running
        admissions, no held semaphore permits. New queries admitted
        while fenced in 'degrade' mode run on the CPU rung and never
        take device permits, so the drain converges."""
        from spark_rapids_tpu.runtime import admission, semaphore

        deadline = time.monotonic() + self.drain_timeout_ms / 1000.0
        while time.monotonic() < deadline:
            ctrl = admission.get()
            with ctrl._cv:
                running = len(ctrl._running)
            if running == 0 and semaphore.get().holders() == 0:
                return True
            with self._cv:
                self._cv.wait(0.01)
        return False

    def _recover(self, t0: float) -> None:
        global _EPOCH
        from spark_rapids_tpu.obs import events as obs_events

        drained = self._await_drain()
        if not drained:
            with self._cv:
                self._stats["drainTimeouts"] += 1
        with self._cv:
            _EPOCH += 1  # exactly once per fence
            new_epoch = _EPOCH
        restorable = dropped = 0
        try:
            self._rebuild_backend()
            restorable, dropped = self._invalidate_device_state()
            clear_chip_fences()
        finally:
            ms = (time.monotonic() - t0) * 1000.0
            with self._cv:
                self._stats["recoveries"] += 1
                self._stats["buffersDropped"] += dropped
                self._stats["buffersRestorable"] += restorable
                self.last_recovery_ms = ms
                self._fenced = False
                self._fence_cause = ""
                self._cv.notify_all()
            obs_events.emit(
                "device.recovery", epoch=new_epoch,
                ms=round(ms, 3), drained=drained,
                restorableBuffers=restorable, droppedBuffers=dropped)
            # the recovery window on the (cross-query) span surface —
            # the fence has no single owning query, so the span hangs
            # off whatever scope observes it (usually none)
            obs_events.emit(
                "operator.span", operator="DeviceRecovery",
                metric="recoveryMs", wallNs=int(ms * 1_000_000),
                deviceNs=0)
            self._notify_admission()

    def _rebuild_backend(self) -> None:
        """Tear down and lazily rebuild the PJRT client. Dead arrays
        are unreachable by construction once the drain finished (every
        stale handle raises before dispatch), so dropping the client
        is safe; the next device_put initializes a fresh backend."""
        import jax

        jax.clear_caches()
        if not self.rebuild_backend:
            return
        try:
            import jax.extend as jex

            jex.backend.clear_backends()
        except Exception:
            # jax version without the API, or a wedged client refusing
            # teardown: epoch checks still fence every stale handle,
            # and the next dispatch re-raises if the device is dead
            pass

    def _invalidate_device_state(self):
        """Drop every pre-epoch device residue: DEVICE-tier spillables
        (host/disk tiers survive for lazy restore), the encoded
        dictionary device cache, warm AOT executables, and mark the
        HBM occupancy timeline."""
        from spark_rapids_tpu.columnar import encoding
        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime import compile_cache, memory

        restorable = dropped = 0
        catalog = memory._catalog
        if catalog is not None:
            restorable, dropped = catalog.on_device_lost()
        encoding.invalidate_device_cache()
        compile_cache.invalidate_warm()
        telemetry.hbm_epoch_marker(_EPOCH)
        return restorable, dropped

    def _notify_admission(self) -> None:
        """Wake queued submissions parked behind the fence."""
        from spark_rapids_tpu.runtime import admission

        ctrl = admission.get()
        with ctrl._cv:
            ctrl._cv.notify_all()

    # --- waiters ---

    def await_ready(self, timeout_s: Optional[float] = None) -> bool:
        """Block until the fence lifts (the resubmission path's wait);
        True when unfenced within the timeout."""
        if timeout_s is None:
            timeout_s = self.recovery_timeout_ms / 1000.0
        deadline = time.monotonic() + timeout_s
        with self._cv:
            while self._fenced:
                left = deadline - time.monotonic()
                if left <= 0:
                    return False
                self._cv.wait(left)
        return True


# ------------------------------------------------------ process wiring

_monitor = DeviceMonitor()
_lock = threading.Lock()


def get() -> DeviceMonitor:
    return _monitor


def install(monitor: DeviceMonitor) -> DeviceMonitor:
    global _monitor
    with _lock:
        _monitor = monitor
    return monitor


def configure(conf=None) -> DeviceMonitor:
    """Session-lifecycle hook (plugin.py TpuExecutorPlugin.init):
    rebuild the monitor from spark.rapids.tpu.device.recovery.*. The
    epoch is process-global and survives reconfiguration — stale
    handles from before a session cycle must stay stale."""
    from spark_rapids_tpu.config import rapids_conf as rc

    def get_(entry):
        return conf.get(entry) if conf is not None else entry.default

    return install(DeviceMonitor(
        enabled=bool(get_(rc.DEVICE_RECOVERY_ENABLED)),
        fenced_admission=get_(rc.DEVICE_RECOVERY_FENCED_ADMISSION),
        resubmit=bool(get_(rc.DEVICE_RECOVERY_RESUBMIT)),
        drain_timeout_ms=get_(rc.DEVICE_RECOVERY_DRAIN_TIMEOUT_MS),
        recovery_timeout_ms=get_(rc.DEVICE_RECOVERY_TIMEOUT_MS),
        rebuild_backend=bool(get_(rc.DEVICE_RECOVERY_REBUILD_BACKEND))))


def counters() -> Dict[str, int]:
    return _monitor.counters()


# ------------------------------------------------------ per-chip fence
#
# Process-wide fencing (above) is the hammer: ONE dead device takes the
# whole backend through drain/epoch-bump/rebuild. Multichip meshes
# deserve a scalpel — when chip k of n dies mid-collective, only its
# shards are lost; the other chips' HBM, compile cache, and in-flight
# work on other queries are intact. The mesh engine fences just the
# lost chip here, rebuilds its mesh over the survivors (keyed by the
# chip epoch so cached shard_map programs for the old topology are
# never reused), and recovers the lost shards from lineage by
# deterministic re-ingestion. A process-wide recovery clears the chip
# fence — the rebuilt backend starts with every device healthy.

_fenced_chips: set = set()
_chip_epoch = 0


def fence_chip(device_id: int, cause: str = "") -> int:
    """Fence ONE chip out of mesh execution; returns the new chip
    epoch. Idempotent per chip (re-fencing a fenced chip does not bump
    the epoch again)."""
    global _chip_epoch
    from spark_rapids_tpu.obs import events as obs_events

    mon = _monitor
    with mon._cv:
        if device_id in _fenced_chips:
            return _chip_epoch
        _fenced_chips.add(device_id)
        _chip_epoch += 1
        mon._stats["chipFences"] += 1
        epoch = _chip_epoch
    obs_events.emit("chip.fence", device=device_id, chipEpoch=epoch,
                    cause=cause)
    return epoch


def unfence_chip(device_id: int) -> None:
    """Return a chip to mesh service (operator action / post-repair)."""
    global _chip_epoch
    from spark_rapids_tpu.obs import events as obs_events

    mon = _monitor
    with mon._cv:
        if device_id not in _fenced_chips:
            return
        _fenced_chips.discard(device_id)
        _chip_epoch += 1
        epoch = _chip_epoch
    obs_events.emit("chip.unfence", device=device_id, chipEpoch=epoch)


def note_chip_recovery() -> None:
    with _monitor._cv:
        _monitor._stats["chipRecoveries"] += 1


def fenced_chips() -> set:
    with _monitor._cv:
        return set(_fenced_chips)


def chip_epoch() -> int:
    return _chip_epoch


def clear_chip_fences() -> None:
    """Process-wide recovery rebuilt the backend: every device is new,
    so per-chip (and per-host) fences from the old epoch no longer
    apply."""
    global _chip_epoch
    with _monitor._cv:
        if _fenced_chips or _fenced_hosts:
            _fenced_chips.clear()
            _fenced_hosts.clear()
            _chip_epoch += 1


# ------------------------------------------------------ per-host fence
#
# One rung up from the per-chip scalpel: on a TPU pod the real failure
# unit is a HOST — one process owns one host's chips, and when that
# process dies (heartbeat silence, dcn collective failure, kill -9)
# every chip it owned is gone at once. fence_host evicts the whole
# group in ONE step (one chip-epoch bump, so the mesh rebuilds exactly
# once rather than once per chip), the mesh engine re-plans over the
# surviving hosts, and the serve layer flips only capacity — /readyz
# stays ready with `fencedHosts` reported. unfence_host is the
# host-rejoin path (repaired host re-registers): its chips return to
# service and capacity bumps back.

_fenced_hosts: Dict[str, tuple] = {}  # host_id -> fenced device ids


def fence_host(host_id, device_ids, cause: str = "") -> int:
    """Fence every chip of one host in a single step; returns the new
    chip epoch. Idempotent per host (re-fencing bumps nothing)."""
    global _chip_epoch
    from spark_rapids_tpu.obs import events as obs_events

    hid = str(host_id)
    mon = _monitor
    with mon._cv:
        if hid in _fenced_hosts:
            return _chip_epoch
        ids = tuple(int(d) for d in device_ids)
        _fenced_hosts[hid] = ids
        _fenced_chips.update(ids)
        _chip_epoch += 1
        mon._stats["hostFences"] += 1
        epoch = _chip_epoch
    obs_events.emit("host.fence", host=hid, devices=list(ids),
                    chipEpoch=epoch, cause=cause)
    return epoch


def unfence_host(host_id) -> None:
    """Return a repaired host's chips to mesh service (the rejoin
    path: capacity bumps back up on the next mesh build)."""
    global _chip_epoch
    from spark_rapids_tpu.obs import events as obs_events

    hid = str(host_id)
    mon = _monitor
    with mon._cv:
        ids = _fenced_hosts.pop(hid, None)
        if ids is None:
            return
        _fenced_chips.difference_update(ids)
        _chip_epoch += 1
        epoch = _chip_epoch
    obs_events.emit("host.unfence", host=hid, devices=list(ids),
                    chipEpoch=epoch)


def note_host_recovery() -> None:
    with _monitor._cv:
        _monitor._stats["hostRecoveries"] += 1


def fenced_hosts() -> list:
    """Sorted ids of the currently host-fenced failure domains."""
    with _monitor._cv:
        return sorted(_fenced_hosts)


# ------------------------------------------------------- use-site API

def check_stale(epoch: Optional[int], what: str) -> None:
    """The stale-handle gate every device-buffer USE runs through: a
    handle stamped before the current epoch references memory the dead
    backend owned — raise instead of touching it."""
    if epoch is not None and epoch != _EPOCH:
        mon = _monitor
        mon.note_stale_handle()
        raise DeviceLostError(
            f"stale device handle: {what} was created in device epoch "
            f"{epoch}, current epoch is {_EPOCH} (the device was lost "
            f"and recovered in between; recompute or re-upload)",
            epoch=epoch)


def check_batch(batch) -> None:
    """Stale-epoch check over a ColumnBatch's columns (dispatch-input
    gate; BuildTable wrappers are unwrapped like encoding_key does).
    Columns built inside traces re-stamp at the current epoch, so only
    genuinely pre-recovery uploads trip this."""
    cols = getattr(batch, "columns", None)
    if cols is None:
        inner = getattr(batch, "batch", None)
        cols = getattr(inner, "columns", None)
    if not cols:
        return
    for c in cols:
        check_stale(getattr(c, "epoch", None), "batch column")


@contextlib.contextmanager
def guard(site: str, detail: str = "", inject: bool = False):
    """Classification wrapper for one dispatch/transfer site. With
    `inject`, the site is also a `device.fatal` chaos site (the fault
    is raised inside the guard so it is classified, fenced, and
    recovered exactly like a real fatal error — never absorbed by the
    degrade ladder's InjectedFault handling)."""
    from spark_rapids_tpu.runtime import faults

    try:
        if inject:
            faults.maybe_inject("device.fatal", detail=detail or site)
        yield
    except DeviceLostError:
        raise  # already classified (stale handle / nested guard)
    except Exception as e:
        if _monitor.enabled and classify(e) == "fatal":
            raise _monitor.report_fatal(e, site) from e
        # recovery disabled: the raw error propagates to the legacy
        # fatal-error policy (plugin.on_task_failed) / its own handler
        raise
