"""Runtime concurrency sanitizer — wait-for-graph deadlock detection,
victim unwind, and permit acquisition-order auditing.

The engine has three blocking resource classes a query can hold while
waiting on another: device-semaphore permit chunks
(runtime/semaphore.py), per-query device-quota reservations
(runtime/memory.py SpillCatalog), and admission slots
(runtime/admission.py). A cycle across them is a silent process wedge —
exactly the failure class an interactive-concurrency accelerator
service cannot tolerate ("Accelerating Presto with GPUs", PAPERS.md),
and exactly what two concurrent per-operator queries used to do to the
semaphore before the atomic-query-group fix.

Design (conf-gated by `spark.rapids.tpu.sanitizer.enabled`):

- **Holders registry**: every instrumented acquire/release reports
  (resource, owner query id, timestamp); the sanitizer never guesses at
  ownership from the outside.
- **Wait-for graph**: every instrumented blocking wait registers an
  edge `waiter -> resource` before parking; resources map to their
  holders, so the graph walked for cycles is
  waiter -> resource -> holder -> (resource that holder waits on) -> …
  Cycle detection runs ON EDGE INSERTION — a deadlock is detected the
  moment the closing edge appears, not by a watchdog poll.
- **Victim unwind**: on a cycle, one WAITING member is selected by
  `sanitizer.deadlock.victimPolicy` (youngest query id by default) and
  unwound through the existing cancel machinery: its CancelToken is
  cancelled with DeadlockDetectedError naming the full cycle, which
  wakes the parked wait (semaphore waits register on_cancel wakeups)
  and rides every PR-5 yield point out of execution, releasing permits
  and spill-catalog buffers leak-free. Waits without a token fall back
  to a `victim_error` flag + wake callback on the wait record itself.
- **Order history**: independent of deadlocks, the sanitizer records
  the global order in which resource CLASSES are acquired while others
  are held (per-thread hold stacks) and flags an INVERSION the first
  time both A-before-B and B-before-A are observed — the lock-order
  lint that catches tomorrow's deadlock in today's clean run.

Observability: `sanitizer.deadlock` / `sanitizer.inversion` obs events,
counters in `session.robustness_metrics["sanitizer"]`, Prometheus
`srtpu_sanitizer_{cycles,inversions,victims}_total`, and a line in
`report.profile()` so recoveries land in the audit trail.

Disabled mode is a None-check: `active()` returns None and no hook
touches a lock or allocates.
"""

from __future__ import annotations

import threading
import time
from typing import Callable, Dict, List, Optional, Set, Tuple

from spark_rapids_tpu.runtime.errors import DeadlockDetectedError

#: Resource identity: (class, key). Classes are the three blocking
#: families; key distinguishes instances within a class.
Resource = Tuple[str, str]

SEMAPHORE: Resource = ("semaphore", "device")
ADMISSION: Resource = ("admission", "slots")


def quota_resource(pool: str = "device") -> Resource:
    return ("quota", pool)


class WaitRecord:
    """One parked (or spinning) wait: who waits, on what, since when,
    how to wake it, and — when victimized without a CancelToken — the
    error its wait loop must raise."""

    __slots__ = ("owner", "resource", "since", "token", "wake",
                 "victim_error", "soft")

    def __init__(self, owner: int, resource: Resource, token=None,
                 wake: Optional[Callable[[], None]] = None,
                 soft: bool = False):
        self.owner = owner
        self.resource = resource
        self.since = time.monotonic()
        self.token = token
        self.wake = wake
        self.victim_error: Optional[BaseException] = None
        self.soft = soft  # retry-loop contention, not a parked thread

    def check(self) -> None:
        """Called by the instrumented wait loop on every wakeup: a
        victimized token-less waiter leaves through here."""
        if self.victim_error is not None:
            raise self.victim_error


class _Counters:
    def __init__(self):
        self.cycles = 0
        self.inversions = 0
        self.victims = 0


class ConcurrencySanitizer:
    """Process-wide wait-for graph + acquisition-order history."""

    def __init__(self, victim_policy: str = "youngest"):
        self.victim_policy = victim_policy
        self._lock = threading.Lock()
        # resource -> {owner qid -> (hold count, first-held ts)}
        self._holders: Dict[Resource, Dict[int, Tuple[int, float]]] = {}
        # owner qid -> live WaitRecords (one thread each, but a query's
        # pool threads can park on several resources at once)
        self._waits: Dict[int, List[WaitRecord]] = {}
        self._tls = threading.local()
        # acquisition-order history over resource classes:
        # first-observed edges {(before_cls, after_cls)}, inversions
        # reported once per unordered pair
        self._order_edges: Set[Tuple[str, str]] = set()
        self._inverted_pairs: Set[Tuple[str, str]] = set()
        self.counters = _Counters()
        self.last_cycle: Optional[List[dict]] = None

    # ------------------------------------------------------- holders

    def _held_stack(self) -> List[Resource]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = self._tls.stack = []
        return st

    def acquired(self, resource: Resource, owner: int) -> None:
        """Record one granted hold of `resource` by query `owner` and
        update the global acquisition-order history."""
        inversion = None
        with self._lock:
            holds = self._holders.setdefault(resource, {})
            n, since = holds.get(owner, (0, time.monotonic()))
            holds[owner] = (n + 1, since)
            # order history: per-THREAD stack — order is a property of
            # one control flow, not of the whole query
            stack = self._held_stack()
            for held in stack:
                if held[0] != resource[0]:
                    inversion = self._note_order_locked(held[0],
                                                        resource[0])
            stack.append(resource)
        if inversion is not None:
            self._emit_inversion(*inversion)

    def released(self, resource: Resource, owner: int) -> None:
        with self._lock:
            holds = self._holders.get(resource)
            if holds and owner in holds:
                n, since = holds[owner]
                if n <= 1:
                    del holds[owner]
                else:
                    holds[owner] = (n - 1, since)
            stack = self._held_stack()
            if resource in stack:
                # remove the most recent hold of this resource
                for i in range(len(stack) - 1, -1, -1):
                    if stack[i] == resource:
                        del stack[i]
                        break

    def holders(self, resource: Resource) -> Dict[int, Tuple[int, float]]:
        with self._lock:
            return dict(self._holders.get(resource, {}))

    def report_holders(self, resource: Resource,
                       owners: Dict[int, float]) -> None:
        """Sync a SOFT resource's holder set from its authoritative
        external ledger (e.g. the SpillCatalog per-query reservation
        map) — used by retry-loop resources where per-reservation
        acquire/release hooks would churn the hot path; callers sync
        right before `note_contention`, so the graph is fresh exactly
        when a cycle could close."""
        with self._lock:
            self._holders[resource] = {
                q: (1, ts) for q, ts in owners.items()}

    # ------------------------------------------------- order history

    def _note_order_locked(self, before: str, after: str):
        """Under _lock: record `before acquired-before after`; return
        the pair when this completes an inversion (both directions now
        observed), else None."""
        edge = (before, after)
        if edge in self._order_edges:
            return None
        self._order_edges.add(edge)
        if (after, before) in self._order_edges:
            pair = tuple(sorted((before, after)))
            if pair not in self._inverted_pairs:
                self._inverted_pairs.add(pair)
                self.counters.inversions += 1
                return (before, after)
        return None

    def _emit_inversion(self, before: str, after: str) -> None:
        from spark_rapids_tpu.obs import events as obs_events

        obs_events.emit("sanitizer.inversion", first=after,
                        second=before,
                        detail=f"resource classes acquired in both "
                               f"orders: {after}->{before} and "
                               f"{before}->{after}")

    def order_history(self) -> Set[Tuple[str, str]]:
        with self._lock:
            return set(self._order_edges)

    def inversions(self) -> Set[Tuple[str, str]]:
        with self._lock:
            return set(self._inverted_pairs)

    # --------------------------------------------------------- waits

    def begin_wait(self, resource: Resource, owner: int, token=None,
                   wake: Optional[Callable[[], None]] = None,
                   soft: bool = False) -> WaitRecord:
        """Insert the wait-for edge `owner -> resource` and run cycle
        detection. Returns the WaitRecord the instrumented wait loop
        must `check()` on wakeups and pass to `end_wait` when done.
        When the inserted edge closes a cycle the victim is unwound
        BEFORE this returns — a deadlock never outlives the edge
        insertion that completed it."""
        if token is None:
            from spark_rapids_tpu.runtime import cancellation

            token = cancellation.current()
        rec = WaitRecord(owner, resource, token=token, wake=wake,
                         soft=soft)
        with self._lock:
            self._waits.setdefault(owner, []).append(rec)
            cycle = self._find_cycle_locked(owner)
        if cycle:
            self._on_cycle(cycle)
        return rec

    def end_wait(self, rec: WaitRecord) -> None:
        with self._lock:
            lst = self._waits.get(rec.owner)
            if lst and rec in lst:
                lst.remove(rec)
                if not lst:
                    del self._waits[rec.owner]

    def note_contention(self, resource: Resource, owner: int,
                        token=None) -> None:
        """Soft wait for retry-loop resources (the quota/pool classes
        raise TpuRetryOOM and spin rather than parking): insert the
        edge + cycle-check once, then remove it — the loop re-notes on
        every failed attempt, so a real cycle re-closes immediately
        while a transient squeeze leaves no residue."""
        rec = self.begin_wait(resource, owner, token=token, soft=True)
        self.end_wait(rec)

    # ---------------------------------------------------- cycle hunt

    def _find_cycle_locked(self, start: int) -> Optional[List[dict]]:
        """DFS from `start` over waiter -> holders(resource waited on).
        Returns the cycle as rows of {queryId, resource, heldFor} or
        None. Runs under _lock; the graph is small (live queries)."""
        path: List[Tuple[int, Resource]] = []
        on_path: Set[int] = set()

        def dfs(q: int) -> Optional[int]:
            on_path.add(q)
            for rec in self._waits.get(q, ()):  # noqa: B020
                holds = self._holders.get(rec.resource, {})
                for holder in holds:
                    if holder == q:
                        continue
                    path.append((q, rec.resource))
                    if holder in on_path:
                        path.append((holder, rec.resource))
                        return holder
                    got = dfs(holder)
                    if got is not None:
                        return got
                    path.pop()
            on_path.discard(q)
            return None

        anchor = dfs(start)
        if anchor is None:
            return None
        # trim the path to the cycle proper (drop any lead-in)
        idx = next(i for i, (q, _r) in enumerate(path) if q == anchor)
        now = time.monotonic()
        rows = []
        for q, res in path[idx:]:
            holds = {r: h[q] for r, h in self._holders.items()
                     if q in h}
            held_for = max((now - since for _n, since in
                            holds.values()), default=0.0)
            rows.append({
                "queryId": q,
                "waitsOn": f"{res[0]}:{res[1]}",
                "holds": sorted(f"{r[0]}:{r[1]}" for r in holds),
                "heldForS": round(held_for, 3),
            })
        # drop the duplicated anchor row at the end
        if len(rows) > 1 and rows[-1]["queryId"] == rows[0]["queryId"]:
            rows.pop()
        return rows

    # ------------------------------------------------ victim unwind

    def _on_cycle(self, cycle: List[dict]) -> None:
        from spark_rapids_tpu.obs import events as obs_events

        with self._lock:
            self.counters.cycles += 1
            self.last_cycle = cycle
            victim_rec = self._pick_victim_locked(cycle)
        desc = "; ".join(
            f"query {r['queryId']} holds {r['holds']} waits on "
            f"{r['waitsOn']} (held {r['heldForS']}s)" for r in cycle)
        obs_events.emit(
            "sanitizer.deadlock",
            cycle=cycle,
            victim=victim_rec.owner if victim_rec else None,
            policy=self.victim_policy)
        if victim_rec is None:
            return  # nothing unwindable: surfaced, counted, not fixed
        with self._lock:
            self.counters.victims += 1
        err = DeadlockDetectedError(
            f"query {victim_rec.owner} unwound as deadlock victim "
            f"(policy={self.victim_policy}); wait-for cycle: [{desc}]")
        if victim_rec.token is not None:
            victim_rec.token.cancel(
                f"deadlock victim (policy={self.victim_policy}); "
                f"wait-for cycle: [{desc}]",
                DeadlockDetectedError)
        else:
            victim_rec.victim_error = err
        if victim_rec.wake is not None:
            try:
                victim_rec.wake()
            except Exception:
                pass  # a wake failure must not poison the detector

    def _pick_victim_locked(self, cycle: List[dict]
                            ) -> Optional[WaitRecord]:
        """Among the cycle's members that are actually WAITING (only a
        parked wait can be unwound), pick per policy; members whose
        wait cannot be interrupted (no token, no wake, soft) lose the
        election to ones that can."""
        members = [r["queryId"] for r in cycle]
        ordered = sorted(members,
                         reverse=(self.victim_policy == "youngest"))
        best: Optional[WaitRecord] = None
        for q in ordered:
            for rec in self._waits.get(q, ()):
                if rec.token is not None or rec.wake is not None \
                        or not rec.soft:
                    return rec
                if best is None:
                    best = rec
        return best

    # -------------------------------------------------- diagnostics

    def snapshot(self) -> dict:
        with self._lock:
            return {
                "cycles": self.counters.cycles,
                "inversions": self.counters.inversions,
                "victims": self.counters.victims,
                "waiting": sum(len(v) for v in self._waits.values()),
                "trackedResources": len(self._holders),
            }

    def check_clean(self) -> None:
        """Test helper: assert no residual waits or holds."""
        with self._lock:
            live_holds = {r: h for r, h in self._holders.items() if h}
            assert not self._waits, f"residual waits: {self._waits}"
            assert not live_holds, f"residual holds: {live_holds}"


# ---------------------------------------------------- process wiring

_instance: Optional[ConcurrencySanitizer] = None
_lock = threading.Lock()


def active() -> Optional[ConcurrencySanitizer]:
    """The enabled process sanitizer, or None — every hook site is
    `san = sanitizer.active()` + a None-check, so disabled mode costs
    one global load per instrumented operation."""
    return _instance


def install(san: Optional[ConcurrencySanitizer]
            ) -> Optional[ConcurrencySanitizer]:
    global _instance
    with _lock:
        _instance = san
    return san


def configure(conf=None) -> Optional[ConcurrencySanitizer]:
    """Session-lifecycle hook (plugin.py executor init): build or tear
    down the process sanitizer from spark.rapids.tpu.sanitizer.*."""
    from spark_rapids_tpu.config import rapids_conf as rc

    def get_(entry):
        return conf.get(entry) if conf is not None else entry.default

    if not get_(rc.SANITIZER_ENABLED):
        return install(None)
    return install(ConcurrencySanitizer(
        victim_policy=get_(rc.SANITIZER_VICTIM_POLICY)))


def counters() -> dict:
    """Registry view (obs/registry.py robustness_snapshot): zeros when
    the sanitizer is disabled so the key layout stays stable."""
    san = active()
    if san is None:
        return {"cycles": 0, "inversions": 0, "victims": 0,
                "enabled": False}
    snap = san.snapshot()
    return {"cycles": snap["cycles"], "inversions": snap["inversions"],
            "victims": snap["victims"], "enabled": True}
