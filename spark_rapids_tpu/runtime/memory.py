"""Device memory pool + tiered spill catalog.

Reference architecture being reproduced (SURVEY.md section 2.3):
- `RapidsBufferCatalog` (RapidsBufferCatalog.scala:62): catalog of
  spillable buffers across DEVICE -> HOST -> DISK tiers, synchronous
  spill on allocation failure (:592).
- `DeviceMemoryEventHandler`: alloc-failure -> spill-N-bytes callback.
- `SpillableColumnarBatch`: operator state parked spillable between
  per-batch steps (SpillableColumnarBatch.scala).
- `SpillPriorities`: lower value spills first.

TPU redesign: PJRT gives no per-allocation failure callback, so the pool
is a *reservation ledger* sitting in front of JAX: every operator batch
is registered with its byte size; `reserve()` checks the ledger against
the budget, synchronously spilling coldest-first (device_get -> pinned
numpy -> .npy file) until the reservation fits, then raises TpuRetryOOM /
TpuSplitAndRetryOOM exactly where RmmSpark would inject them. Tests force
tiny budgets + injection to exercise every path (the reference's
*RetrySuite strategy, SURVEY.md section 4 tier 2).
"""

from __future__ import annotations

import contextlib
import os
import tempfile
import threading
import time
import uuid
import zipfile
from enum import Enum
from typing import Dict, List, Optional

import jax
import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnBatch
from spark_rapids_tpu.runtime.errors import (
    RetryExhausted,
    SpillFileError,
    TpuRetryOOM,
    TpuSplitAndRetryOOM,
)


class SpillTier(Enum):
    DEVICE = 0
    HOST = 1
    DISK = 2


#: uids of every SpillCatalog constructed by THIS process. The startup
#: orphan sweep removes spill files whose embedded catalog uid is not
#: in this set: a crashed process's leftovers (truncated .inprogress
#: writes AND completed files nothing references anymore) are garbage,
#: while a force-rebuilt session's previous catalog — whose live
#: spillables still reference their files — stays untouched.
_live_catalog_uids = set()
_live_uids_lock = threading.Lock()


class SpillPriority:
    """Lower spills first (reference SpillPriorities.scala)."""

    INPUT_FROM_SHUFFLE = -200
    ACTIVE_BATCHING = -100
    ACTIVE_ON_DECK = 0
    HOST_MEMORY = 100


class SpillableBatch:
    """A registered, spillable columnar batch (SpillableColumnarBatch
    analog). Not thread-safe per instance; the catalog lock serializes
    tier moves."""

    def __init__(self, catalog: "SpillCatalog", batch: ColumnBatch,
                 priority: int, query_id: int = 0):
        self._catalog = catalog
        self._priority = priority
        self._tier = SpillTier.DEVICE
        self._device_batch: Optional[ColumnBatch] = batch
        self._host_data = None
        self._disk_path: Optional[str] = None
        self._treedef = None
        self.query_id = query_id  # owning query (0 = unattributed)
        self.size_bytes = batch.device_size_bytes()
        self._rows = None  # lazy: row_count() syncs the device (64ms+
        # per roundtrip on tunneled devices; hundreds of parks per query)
        self.id = uuid.uuid4().hex[:12]
        self.closed = False
        # device-epoch stamp of the DEVICE-tier copy
        # (runtime/device_monitor.py): a device-loss recovery marks
        # every device-resident buffer lost; host/disk copies survive
        # and re-stamp on unspill
        from spark_rapids_tpu.runtime import device_monitor

        self.device_epoch = device_monitor.current_epoch()
        self._device_lost = False

    @property
    def tier(self) -> SpillTier:
        return self._tier

    def row_count(self) -> int:
        if self._rows is None:
            # the catalog RLock serializes against tier moves
            # (_to_host/_to_disk also run under it); whichever tier the
            # batch is on, its copy carries the count
            with self._catalog._lock:
                if self._rows is None:
                    if self._device_batch is not None:
                        self._rows = self._device_batch.row_count()
                    elif self._host_data is not None:
                        # num_rows is the LAST pytree leaf
                        self._rows = int(self._host_data[-1])
                    elif self._disk_path is not None:
                        def last():
                            with np.load(self._disk_path) as z:
                                return int(z[z.files[-1]])

                        self._rows = self._disk_io(
                            last, "read", self._disk_path)
                    else:
                        raise RuntimeError(
                            "row_count() on a closed SpillableBatch")
        return self._rows

    # --- tier transitions (called under catalog lock) ---

    def _to_host(self):
        assert self._tier == SpillTier.DEVICE
        import time as _time

        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime.profiler import annotate

        leaves, treedef = jax.tree_util.tree_flatten(self._device_batch)
        t0 = _time.monotonic_ns()
        with annotate(f"spill:D2H:{self.size_bytes}"):
            self._host_data = [np.asarray(jax.device_get(x))
                               for x in leaves]
        telemetry.record("d2h", "spill.toHost", self.size_bytes,
                         ns=_time.monotonic_ns() - t0,
                         query_id=self.query_id)
        self._treedef = treedef
        self._device_batch = None
        self._tier = SpillTier.HOST

    def _disk_io(self, fn, op: str, path: str):
        """Run one disk-tier spill read/write under the spill.disk
        backoff policy; terminal failure surfaces as a SpillFileError
        naming this buffer's id, tier, and path — never a raw
        numpy/OSError through an operator. A MISSING spill file is
        immediate (deleted out from under us: not transient)."""
        from spark_rapids_tpu.runtime import backoff

        try:
            return backoff.retry_io(
                fn, what=f"spill {op} {path}", site="spill.disk",
                retry_on=(OSError, ValueError, zipfile.BadZipFile,
                          EOFError),
                no_retry=(FileNotFoundError,), counter="spill.disk")
        except FileNotFoundError as e:
            raise SpillFileError(self.id, self._tier.name, path,
                                 op=op) from e
        except RetryExhausted as e:
            raise SpillFileError(self.id, self._tier.name, path,
                                 op=op) from e

    def _to_disk(self):
        assert self._tier == SpillTier.HOST
        import time as _time

        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime.profiler import annotate

        path = os.path.join(
            self._catalog.spill_dir,
            f"spill-{self._catalog.uid}-{self.id}.npz")

        def write_atomic():
            # crash consistency: a process dying mid-spill must never
            # leave a truncated file a later unspill trusts — write to
            # .inprogress, fsync, then atomically rename into place
            # (the catalog startup sweep reaps orphaned .inprogress
            # files of dead processes)
            tmp = path + ".inprogress"
            with open(tmp, "wb") as f:
                np.savez(f, *self._host_data)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

        t0 = _time.monotonic_ns()
        with annotate(f"spill:HOST2DISK:{self.size_bytes}"):
            self._disk_io(write_atomic, "write", path)
        telemetry.record("spill-disk", "spill.toDisk", self.size_bytes,
                         ns=_time.monotonic_ns() - t0,
                         query_id=self.query_id)
        self._disk_path = path
        self._host_data = None
        self._tier = SpillTier.DISK

    def _host_from_disk(self):
        assert self._tier == SpillTier.DISK
        import time as _time

        from spark_rapids_tpu.obs import telemetry

        def load():
            with np.load(self._disk_path) as z:
                return [z[k] for k in z.files]

        t0 = _time.monotonic_ns()
        self._host_data = self._disk_io(load, "read", self._disk_path)
        telemetry.record("spill-disk", "spill.fromDisk", self.size_bytes,
                         ns=_time.monotonic_ns() - t0,
                         query_id=self.query_id)
        os.unlink(self._disk_path)
        self._disk_path = None
        self._tier = SpillTier.HOST

    def _to_device(self):
        if self._tier == SpillTier.DISK:
            self._host_from_disk()
        if self._tier == SpillTier.HOST:
            import time as _time

            from spark_rapids_tpu.obs import telemetry
            from spark_rapids_tpu.runtime import device_monitor
            from spark_rapids_tpu.runtime.profiler import annotate

            t0 = _time.monotonic_ns()
            # transfer-site fatal classification + device.fatal chaos:
            # an H2D upload into a dead backend is a fence trigger,
            # not a raw XlaRuntimeError through an operator
            with device_monitor.guard("spill.unspill", inject=True):
                with annotate(f"unspill:H2D:{self.size_bytes}"):
                    leaves = [jax.device_put(x)
                              for x in self._host_data]
            telemetry.record("h2d", "spill.unspill", self.size_bytes,
                             ns=_time.monotonic_ns() - t0,
                             query_id=self.query_id)
            self._device_batch = jax.tree_util.tree_unflatten(
                self._treedef, leaves)
            self._host_data = None
            self._tier = SpillTier.DEVICE
            # freshly uploaded: this copy belongs to the live backend
            from spark_rapids_tpu.runtime import device_monitor

            self.device_epoch = device_monitor.current_epoch()
            self._device_lost = False

    # --- public API ---

    def get_batch(self) -> ColumnBatch:
        """Materialize on device (unspilling if needed; reserves
        budget). A DEVICE-tier copy from a dead epoch raises
        DeviceLostError instead of handing out recycled device memory
        — the buffer was device-only when the device died, so the
        owner must recompute (lineage scheduler / query resubmit)."""
        if self._tier == SpillTier.DEVICE:
            from spark_rapids_tpu.runtime import device_monitor

            if self._device_lost:
                device_monitor.check_stale(
                    self.device_epoch, f"spillable buffer {self.id}")
                # lost flag without an epoch delta cannot happen (the
                # flag is only set by on_device_lost after a bump),
                # but never hand out a lost buffer either way
                from spark_rapids_tpu.runtime.errors import (
                    DeviceLostError,
                )

                raise DeviceLostError(
                    f"spillable buffer {self.id} was device-resident "
                    f"when the device was lost; recompute it",
                    epoch=self.device_epoch)
            device_monitor.check_stale(
                self.device_epoch, f"spillable buffer {self.id}")
        self._catalog.unspill(self)
        return self._device_batch

    def close(self):
        if self.closed:
            return
        self.closed = True
        self._catalog.remove(self)
        if self._disk_path and os.path.exists(self._disk_path):
            os.unlink(self._disk_path)
        self._device_batch = None
        self._host_data = None


class DeviceMemoryPool:
    """Reservation ledger for device HBM (the Rmm pool analog). Every
    successful reserve/release feeds the telemetry occupancy timeline
    (obs/telemetry.py) with the post-op total, so HBM occupancy over
    time is a recorded series, not a point probe."""

    def __init__(self, limit_bytes: int):
        self.limit = limit_bytes
        self.reserved = 0
        self.peak = 0
        self._lock = threading.RLock()

    def try_reserve(self, nbytes: int) -> bool:
        from spark_rapids_tpu.obs import telemetry

        with self._lock:
            if self.reserved + nbytes > self.limit:
                return False
            self.reserved += nbytes
            self.peak = max(self.peak, self.reserved)
            telemetry.hbm_global(self.reserved)
            return True

    def release(self, nbytes: int):
        from spark_rapids_tpu.obs import telemetry

        with self._lock:
            self.reserved = max(0, self.reserved - nbytes)
            telemetry.hbm_global(self.reserved)


class SpillCatalog:
    """RapidsBufferCatalog analog: tracks spillables, performs synchronous
    coldest-first spill when device reservations fail."""

    def __init__(self, device_limit: int, host_limit: int,
                 spill_dir: Optional[str] = None,
                 oom_injection_mode: str = "none",
                 oom_injection_filter: str = "",
                 oom_dump_dir: str = "",
                 query_quota_bytes: int = 0):
        self.pool = DeviceMemoryPool(device_limit)
        self.host_limit = host_limit
        self.host_used = 0
        self.spill_dir = spill_dir or tempfile.mkdtemp(prefix="srtpu-spill-")
        self.uid = uuid.uuid4().hex[:8]
        with _live_uids_lock:
            _live_catalog_uids.add(self.uid)
        self._buffers: Dict[str, SpillableBatch] = {}
        self._lock = threading.RLock()
        # per-query DEVICE reservation ledger (the quota unit,
        # spark.rapids.tpu.quota.device.maxBytesPerQuery); its own lock
        # because reserve() runs outside the catalog lock
        self.query_quota_bytes = max(0, int(query_quota_bytes))
        self._q_dev: Dict[int, int] = {}
        self._q_lock = threading.Lock()
        self._oom_mode = oom_injection_mode
        self._oom_filter = oom_injection_filter
        self._oom_dump_dir = oom_dump_dir
        self._oom_armed = oom_injection_mode in ("once", "always",
                                                 "split_once")
        self.metrics = {
            "spill_to_host": 0, "spill_to_disk": 0, "unspill": 0,
            "retry_oom_injected": 0, "quota_oom": 0,
            "orphaned_files_swept": 0, "device_lost_buffers": 0,
        }
        self._sweep_orphans()

    def _sweep_orphans(self) -> None:
        """Catalog-startup crash recovery: remove spill files owned by
        no live catalog of this process — truncated `.inprogress`
        writes AND completed files a dead process left behind (a crash
        loses every in-memory reference, so they are unreachable).
        Counted in metrics['orphaned_files_swept'] (the
        spill.orphanedFiles robustness metric)."""
        try:
            names = os.listdir(self.spill_dir)
        except OSError:
            return
        with _live_uids_lock:
            live = set(_live_catalog_uids)
        swept = 0
        for name in names:
            core = name[:-len(".inprogress")] \
                if name.endswith(".inprogress") else name
            if not (core.startswith("spill-") and core.endswith(".npz")):
                continue
            parts = core[len("spill-"):-len(".npz")].split("-")
            owner = parts[0] if len(parts) >= 2 else ""
            if owner in live:
                # a live catalog's file: completed files are
                # referenced by its spillables; an .inprogress file
                # may be a concurrent in-flight write — never touch
                continue
            try:
                os.unlink(os.path.join(self.spill_dir, name))
                swept += 1
            except OSError:
                pass
        self.metrics["orphaned_files_swept"] = swept

    # --- registration ---

    def add_batch(self, batch: ColumnBatch,
                  priority: int = SpillPriority.ACTIVE_ON_DECK
                  ) -> SpillableBatch:
        from spark_rapids_tpu.obs import events as obs_events

        qid = obs_events.effective_query_id()
        sb = SpillableBatch(self, batch, priority, query_id=qid)
        self.reserve(sb.size_bytes, tag="add_batch", query_id=qid)
        from spark_rapids_tpu.runtime import faults

        if faults.should_inject("device.lost_buffer"):
            # chaos site device.lost_buffer: poison THIS buffer's
            # device epoch so its next use hits the stale-handle gate
            # deterministically — the proof that pre-epoch handles
            # raise DeviceLostError instead of reading recycled memory
            sb.device_epoch -= 1
        with self._lock:
            self._buffers[sb.id] = sb
        return sb

    def remove(self, sb: SpillableBatch):
        with self._lock:
            if self._buffers.pop(sb.id, None) is None:
                return
            if sb.tier == SpillTier.DEVICE:
                if sb._device_lost:
                    # reservation already released by on_device_lost
                    # (the dead backend freed the HBM); a second
                    # release would corrupt the ledger
                    return
                self.pool.release(sb.size_bytes)
                self._q_release(sb.query_id, sb.size_bytes)
            elif sb.tier == SpillTier.HOST:
                self.host_used -= sb.size_bytes
                from spark_rapids_tpu.runtime import host_alloc

                host_alloc.get().pageable.release(sb.size_bytes)

    # --- reservation with synchronous spill ---

    def _maybe_inject_oom(self, tag: str):
        if not self._oom_armed:
            return
        if self._oom_filter and self._oom_filter not in tag:
            return
        if self._oom_mode in ("once", "split_once"):
            self._oom_armed = False
        self.metrics["retry_oom_injected"] += 1
        if self._oom_mode == "split_once":
            raise TpuSplitAndRetryOOM(f"injected split OOM at {tag}")
        raise TpuRetryOOM(f"injected OOM at {tag}")

    # --- per-query quota ledger (all under _q_lock) ---

    @staticmethod
    def _resolve_qid(query_id: Optional[int]) -> int:
        if query_id is not None:
            return query_id
        from spark_rapids_tpu.obs import events as obs_events

        return obs_events.effective_query_id()

    def _q_add(self, qid: int, nbytes: int) -> None:
        if not qid:
            return
        from spark_rapids_tpu.obs import telemetry

        with self._q_lock:
            cur = self._q_dev[qid] = self._q_dev.get(qid, 0) + nbytes
            telemetry.hbm_query(qid, cur)

    def _q_release(self, qid: int, nbytes: int) -> None:
        if not qid:
            return
        from spark_rapids_tpu.obs import telemetry

        with self._q_lock:
            left = self._q_dev.get(qid, 0) - nbytes
            if left > 0:
                self._q_dev[qid] = left
            else:
                self._q_dev.pop(qid, None)
            telemetry.hbm_query(qid, max(0, left))

    def query_device_reserved(self, query_id: int) -> int:
        with self._q_lock:
            return self._q_dev.get(query_id, 0)

    def _quota_admit(self, qid: int, nbytes: int, tag: str) -> None:
        """Per-query quota gate: an over-quota reservation first spills
        the OFFENDING query's own device buffers, then raises a
        retry-class OOM for that query only — session-wide pressure
        stays untouched (the Vortex capacity-isolation stance)."""
        quota = self.query_quota_bytes
        if not qid or quota <= 0:
            return
        with self._q_lock:
            cur = self._q_dev.get(qid, 0)
        if cur + nbytes <= quota:
            return
        freed = self.spill_device_bytes(cur + nbytes - quota,
                                        query_id=qid)
        with self._q_lock:
            cur = self._q_dev.get(qid, 0)
        if cur + nbytes <= quota:
            return
        self.metrics["quota_oom"] += 1
        if freed > 0:
            raise TpuRetryOOM(
                f"query {qid} over device quota reserving {nbytes} "
                f"(tag={tag}, quota={quota}, reserved={cur}); spilled "
                f"{freed} of its bytes, retry")
        raise TpuSplitAndRetryOOM(
            f"query {qid} device quota {quota} cannot fit {nbytes} "
            f"(tag={tag}, reserved={cur}); split the input and retry")

    def _note_quota_contention(self, qid: int) -> None:
        """Sanitizer hook at a failed reservation: sync the quota
        resource's holder set from the per-query ledger, then insert
        the transient wait-for edge (cycle detection runs on the
        insertion). A query spinning in TpuRetryOOM because OTHER
        queries' reservations fill the device is waiting on them just
        as surely as a parked semaphore ticket — this is what closes
        cross-class cycles (hold permits, wait memory / hold memory,
        wait permits)."""
        from spark_rapids_tpu.runtime import sanitizer as _san

        san = _san.active()
        if san is None:
            return
        now = time.monotonic()
        with self._q_lock:
            owners = {q: now for q, b in self._q_dev.items()
                      if b > 0 and q != qid}
        res = _san.quota_resource()
        san.report_holders(res, owners)
        san.note_contention(res, qid)

    def reserve(self, nbytes: int, tag: str = "",
                query_id: Optional[int] = None):
        """Reserve device bytes; spill synchronously if needed; raise
        TpuRetryOOM when spilling freed something (caller must retry) or
        TpuSplitAndRetryOOM when nothing can free enough. Reservations
        are tagged with the owning query (resolved from the obs task/
        query scope when not passed) and gated by the per-query quota
        BEFORE touching the shared pool."""
        self._maybe_inject_oom(tag)
        qid = self._resolve_qid(query_id)
        self._quota_admit(qid, nbytes, tag)
        if self.pool.try_reserve(nbytes):
            self._q_add(qid, nbytes)
            return
        shortfall = max(0, nbytes - (self.pool.limit - self.pool.reserved))
        freed = self.spill_device_bytes(shortfall)
        if self.pool.try_reserve(nbytes):
            self._q_add(qid, nbytes)
            return
        self._note_quota_contention(qid)
        if freed > 0:
            raise TpuRetryOOM(
                f"device pool exhausted reserving {nbytes} (tag={tag}); "
                f"spilled {freed} bytes, retry")
        # recoverable by design: with_retry splits the input and
        # re-attempts. Dumps happen only at TERMINAL failure sites
        # (runtime/retry.py dump_terminal_oom) so the split-retry hot
        # path stays free of file I/O under the catalog lock.
        raise TpuSplitAndRetryOOM(
            f"device pool cannot fit {nbytes} (tag={tag}, "
            f"limit={self.pool.limit}, reserved={self.pool.reserved}); "
            "split the input and retry")

    def release(self, nbytes: int, query_id: Optional[int] = None):
        self.pool.release(nbytes)
        self._q_release(self._resolve_qid(query_id), nbytes)

    @contextlib.contextmanager
    def reserved(self, nbytes: int, tag: str = ""):
        """Scoped reservation — operators wrap device compute whose
        output is ~nbytes so allocation pressure (and injected OOM)
        surfaces at a retryable point. The owning query is captured at
        entry so the exit releases the same ledger even if the thread's
        scopes changed."""
        from spark_rapids_tpu.runtime import sanitizer as _san

        qid = self._resolve_qid(None)
        self.reserve(nbytes, tag=tag, query_id=qid)
        # acquisition-order history: a scoped reservation is a held
        # resource of class "quota" for the sanitizer's lock-order
        # audit (e.g. taking semaphore permits while inside one is the
        # inversion of the usual permits-then-memory order)
        san = _san.active()
        res = _san.quota_resource("scoped")
        if san is not None:
            san.acquired(res, qid)
        try:
            yield
        finally:
            if san is not None:
                san.released(res, qid)
            self.release(nbytes, query_id=qid)

    def spill_device_bytes(self, target: int,
                           query_id: Optional[int] = None) -> int:
        """Spill coldest (lowest priority, largest first) device buffers
        until `target` bytes are freed (RapidsBufferCatalog.synchronousSpill
        analog). With `query_id` only THAT query's buffers are
        candidates — the quota gate degrades the offending query
        without disturbing its neighbors."""
        from spark_rapids_tpu.obs import telemetry

        telemetry.hbm_pressure(target, 0, query_id=query_id)
        freed = 0
        with self._lock:
            candidates = sorted(
                (b for b in self._buffers.values()
                 if b.tier == SpillTier.DEVICE and not b.closed
                 and not b._device_lost
                 and (query_id is None or b.query_id == query_id)),
                key=lambda b: (b._priority, -b.size_bytes))
            for b in candidates:
                if freed >= target:
                    break
                self._spill_one(b)
                freed += b.size_bytes
        return freed

    def _spill_one(self, b: SpillableBatch):
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import host_alloc

        pageable = host_alloc.get().pageable
        if (self.host_used + b.size_bytes <= self.host_limit
                and pageable.try_reserve(b.size_bytes)):
            b._to_host()
            self.pool.release(b.size_bytes)
            self._q_release(b.query_id, b.size_bytes)
            self.host_used += b.size_bytes
            self.metrics["spill_to_host"] += 1
            obs_events.emit("spill", component="catalog",
                            direction="down", fromTier="DEVICE",
                            toTier="HOST", bytes=b.size_bytes)
            return
        # host tier full (own threshold or the GLOBAL host budget,
        # runtime/host_alloc.py): go straight through to disk. The
        # transient host copy is force-accounted — the spill MUST
        # proceed to relieve HBM pressure, and the ledger staying
        # truthful makes concurrent callers feel the pressure
        pageable.reserve_force(b.size_bytes)
        try:
            b._to_host()
            b._to_disk()
        finally:
            pageable.release(b.size_bytes)
        self.pool.release(b.size_bytes)
        self._q_release(b.query_id, b.size_bytes)
        self.metrics["spill_to_disk"] += 1
        obs_events.emit("spill", component="catalog", direction="down",
                        fromTier="DEVICE", toTier="DISK",
                        bytes=b.size_bytes)

    def spill_host_bytes(self, target: int) -> int:
        """Push coldest host-tier buffers to disk until `target`
        pageable bytes are freed — HostAlloc's pressure valve
        (HostAlloc.scala blocking-alloc spills host store likewise)."""
        from spark_rapids_tpu.runtime import host_alloc

        pageable = host_alloc.get().pageable
        freed = 0
        with self._lock:
            cands = sorted(
                (x for x in self._buffers.values()
                 if x.tier == SpillTier.HOST),
                key=lambda x: (x._priority, -x.size_bytes))
            for hb in cands:
                if freed >= target:
                    break
                hb._to_disk()
                self.host_used -= hb.size_bytes
                pageable.release(hb.size_bytes)
                self.metrics["spill_to_disk"] += 1
                from spark_rapids_tpu.obs import events as obs_events

                obs_events.emit("spill", component="catalog",
                                direction="down", fromTier="HOST",
                                toTier="DISK", bytes=hb.size_bytes)
                freed += hb.size_bytes
        return freed

    def unspill(self, sb: SpillableBatch):
        with self._lock:
            if sb.tier == SpillTier.DEVICE:
                return
            was_host = sb.tier == SpillTier.HOST
            # reserve device room first (may cascade-spill others);
            # the reservation belongs to the buffer's OWNING query, not
            # whichever query happened to trigger the unspill
            self.reserve(sb.size_bytes, tag="unspill",
                         query_id=sb.query_id)
            sb._to_device()
            if was_host:
                self.host_used -= sb.size_bytes
                from spark_rapids_tpu.runtime import host_alloc

                host_alloc.get().pageable.release(sb.size_bytes)
            self.metrics["unspill"] += 1
            from spark_rapids_tpu.obs import events as obs_events

            obs_events.emit(
                "spill", component="catalog", direction="up",
                fromTier="HOST" if was_host else "DISK",
                toTier="DEVICE", bytes=sb.size_bytes)

    def on_device_lost(self):
        """Device-loss recovery hook (runtime/device_monitor.py): the
        dead backend's HBM is gone, so every DEVICE-tier buffer is
        marked lost (its owner's next get_batch raises DeviceLostError
        — recompute via lineage/resubmit) and its pool + per-query
        reservations are released so the ledger describes the FRESH
        backend. HOST/DISK-tier buffers are untouched: they restore
        lazily into the new epoch on their next unspill. Returns
        (restorable, dropped) buffer counts."""
        restorable = dropped = 0
        with self._lock:
            for b in self._buffers.values():
                if b.closed:
                    continue
                if b.tier == SpillTier.DEVICE:
                    if not b._device_lost:
                        b._device_lost = True
                        b._device_batch = None  # never touch it again
                        self.pool.release(b.size_bytes)
                        self._q_release(b.query_id, b.size_bytes)
                        dropped += 1
                else:
                    restorable += 1
            self.metrics["device_lost_buffers"] += dropped
        return restorable, dropped

    # --- stats ---

    def device_reserved(self) -> int:
        return self.pool.reserved

    def buffer_count(self) -> int:
        with self._lock:
            return len(self._buffers)

    def check_leaks(self, raise_on_leak: bool = False) -> int:
        """Leak tracking (MemoryCleaner / TaskRegistryTracker analog,
        reference Plugin.scala:562-577 shutdown-hook accounting): every
        SpillableBatch must be closed by its owning operator. Returns
        the number of live buffers; logs (or raises) when nonzero."""
        with self._lock:
            # close() removes a buffer from the catalog, so anything
            # still registered is by construction unclosed
            leaked = list(self._buffers.values())
        if leaked:
            import logging

            msg = (f"{len(leaked)} spillable buffer(s) leaked "
                   f"({sum(b.size_bytes for b in leaked)} bytes, tiers: "
                   f"{sorted({b.tier.name for b in leaked})})")
            if raise_on_leak:
                raise AssertionError(msg)
            logging.getLogger(__name__).warning(msg)
        return len(leaked)


_catalog: Optional[SpillCatalog] = None
_catalog_lock = threading.Lock()


def initialize_memory(conf=None, force: bool = False) -> SpillCatalog:
    """GpuDeviceManager.initializeMemory analog (reference
    GpuDeviceManager.scala:275-385): size the pool from conf/HBM and
    install the global catalog. force=True rebuilds with the new conf
    (used by session init so startup-only memory confs of a fresh
    session are honored; live spillables keep referencing their old
    catalog until closed)."""
    global _catalog
    from spark_rapids_tpu.config import rapids_conf as rc

    conf = conf or rc.RapidsConf()
    with _catalog_lock:
        if _catalog is not None and not force:
            return _catalog
        limit = conf.get(rc.MEMORY_LIMIT_BYTES)
        if not limit:
            hbm = _detect_hbm_bytes()
            limit = int(hbm * conf.get(rc.MEMORY_FRACTION))
        from spark_rapids_tpu.runtime import host_alloc

        host_alloc.initialize(conf.get(rc.PINNED_POOL_SIZE),
                              conf.get(rc.HOST_MEMORY_LIMIT))
        _catalog = SpillCatalog(
            device_limit=limit,
            host_limit=conf.get(rc.HOST_SPILL_STORAGE_SIZE),
            spill_dir=conf.get(rc.SPILL_DIR) or None,
            oom_injection_mode=conf.get(rc.OOM_INJECTION_MODE),
            oom_injection_filter=conf.get(rc.TEST_RETRY_OOM_INJECTION_FILTER),
            oom_dump_dir=conf.get(rc.OOM_DUMP_DIR),
            query_quota_bytes=conf.get(rc.QUOTA_DEVICE_BYTES_PER_QUERY),
        )
        return _catalog


def _detect_hbm_bytes() -> int:
    try:
        d = jax.devices()[0]
        stats = d.memory_stats()
        if stats and "bytes_limit" in stats:
            return int(stats["bytes_limit"])
    except Exception:
        pass
    # CPU backend / unknown: pretend 16 GiB (v5e HBM size)
    return 16 << 30


def get_catalog() -> SpillCatalog:
    if _catalog is None:
        return initialize_memory()
    return _catalog


def shutdown_memory():
    global _catalog
    with _catalog_lock:
        _catalog = None
