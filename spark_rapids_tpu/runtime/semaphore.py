"""Task-admission semaphore — the GpuSemaphore analog.

Reference (`GpuSemaphore.scala:100-421`): limits how many tasks hold
device memory concurrently; permits = 1000 / concurrentGpuTasks; tracks
wait time for task metrics. Same design: a counted semaphore keyed by
task id so re-entrant acquires are free, with wait-time accounting.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

MAX_PERMITS = 1000


class TpuSemaphore:
    def __init__(self, concurrent_tasks: int = 2):
        concurrent_tasks = max(1, concurrent_tasks)
        self._permits_per_task = max(1, MAX_PERMITS // concurrent_tasks)
        self._available = MAX_PERMITS
        self._cv = threading.Condition()
        self._holders: Dict[int, int] = {}
        self.total_wait_ns = 0

    def acquire_if_necessary(self, task_id: int):
        with self._cv:
            if task_id in self._holders:
                return
            start = time.monotonic_ns()
            while self._available < self._permits_per_task:
                self._cv.wait()
            self.total_wait_ns += time.monotonic_ns() - start
            self._available -= self._permits_per_task
            self._holders[task_id] = self._permits_per_task

    def release_if_necessary(self, task_id: int):
        with self._cv:
            permits = self._holders.pop(task_id, None)
            if permits:
                self._available += permits
                self._cv.notify_all()

    def holders(self) -> int:
        with self._cv:
            return len(self._holders)


_instance: Optional[TpuSemaphore] = None
_lock = threading.Lock()


def initialize(concurrent_tasks: int):
    global _instance
    with _lock:
        old, _instance = _instance, TpuSemaphore(concurrent_tasks)
    if old is not None:
        # wake anyone still blocked on the replaced instance — their
        # releases would otherwise notify only the new one, stranding
        # them on a condition variable nobody signals again
        with old._cv:
            old._available = MAX_PERMITS
            old._cv.notify_all()


def get() -> TpuSemaphore:
    global _instance
    with _lock:
        if _instance is None:
            _instance = TpuSemaphore()
        return _instance
