"""Task-admission semaphore — the GpuSemaphore analog.

Reference (`GpuSemaphore.scala:100-421`): limits how many tasks hold
device memory concurrently; permits = 1000 / concurrentGpuTasks; tracks
wait time for task metrics. Same design: a counted semaphore keyed by
task id so re-entrant acquires are free, with wait-time accounting.

Hardened (PR 2): acquisition honors a conf'd timeout
(`spark.rapids.tpu.semaphore.acquireTimeoutMs`). A leaked permit (a
task that died without releasing) used to hang every later task
forever with zero diagnostics; now the blocked acquire raises
SemaphoreTimeout carrying the held-permit table — which task ids hold
how many permits, owned by which query, for how long — so the operator
sees the culprit (and which query to `session.cancel`) instead of a
silent wedge.

Governance (PR 5):

- **FIFO fairness via ticket ordering**: waiters are served in arrival
  order. The old wake-and-race grant let a stream of late arrivals
  repeatedly slip in front of a parked waiter whenever permits freed
  (each notify_all raced every waiter plus any NEW acquirer that never
  slept) — a heavy waiter could starve indefinitely behind light
  traffic. Now every first-time acquirer takes a monotonically
  increasing ticket and only the front ticket may take permits;
  re-entrant acquires (already holding) remain free.
- **Cooperative cancellation**: an acquire under a query CancelToken
  (runtime/cancellation.py — resolved from the thread scope, or passed
  explicitly) registers a cancel wakeup and leaves the wait promptly
  when the query is cancelled or its deadline passes, removing its
  ticket so the queue never wedges behind a dead waiter.

Deadlock freedom (PR 7, `semaphore.atomicQueryGroups`):

- **Atomic per-query permit groups**: all permits a query ever holds
  form ONE group. The query's FIRST acquire is the group leader: it
  waits ticket-FIFO for a whole permit chunk, holding nothing while it
  waits (all-or-nothing). Every LATER acquire by the same query — a
  nested stage materializing a CPU-fallback subtree, sibling result
  tasks, a shuffle map task under an outer hold — is a group
  EXPANSION: it joins immediately, consuming a free chunk only when
  one is available and nobody is queued ahead, else riding the group's
  existing hold for free. A query therefore NEVER blocks on the
  semaphore while holding permits, which removes the hold-and-wait
  ingredient entirely: two concurrent per-operator queries used to
  interleave partial holds (each scaffold chunk starving the other's
  nested acquire) into a permanent wedge; now each nested acquire
  rides its own query's group and both complete. The legacy per-task
  discipline survives behind the conf (False) so the concurrency
  sanitizer's detection/recovery path stays regression-testable.
- **Sanitizer instrumentation** (runtime/sanitizer.py, conf-gated):
  holds are reported per owning query, a wait-for edge is registered
  before every park, and each wakeup checks the wait record so a
  victimized token-less waiter unwinds with DeadlockDetectedError.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, Optional, Set

from spark_rapids_tpu.runtime.errors import SemaphoreTimeout

MAX_PERMITS = 1000

# chaos site `semaphore.partial_hold`: how long a freshly granted task
# keeps holding before proceeding — long enough that two concurrent
# legacy-path queries always overlap partial holds, short enough that
# the deadlock gates stay fast
PARTIAL_HOLD_S = 0.05


def _should_stall() -> bool:
    from spark_rapids_tpu.runtime import faults
    return faults.should_inject("semaphore.partial_hold")

DEFAULT_ACQUIRE_TIMEOUT_MS = 600_000


class TpuSemaphore:
    def __init__(self, concurrent_tasks: int = 2,
                 acquire_timeout_ms: int = DEFAULT_ACQUIRE_TIMEOUT_MS,
                 atomic_query_groups: bool = True):
        concurrent_tasks = max(1, concurrent_tasks)
        self._permits_per_task = max(1, MAX_PERMITS // concurrent_tasks)
        self._available = MAX_PERMITS
        self._cv = threading.Condition()
        self._holders: Dict[int, int] = {}
        self._held_since: Dict[int, float] = {}
        self._holder_query: Dict[int, int] = {}
        self._query_tasks: Dict[int, Set[int]] = {}
        self._queue: deque = deque()  # tickets, FIFO
        self._ticket = itertools.count(1)
        self._timeout_ms = acquire_timeout_ms
        self._atomic_groups = atomic_query_groups
        self.total_wait_ns = 0
        self.timeouts = 0
        self.cancelled_waits = 0
        self.group_joins = 0
        self.group_rides = 0

    def acquire_if_necessary(self, task_id: int, cancel=None):
        from spark_rapids_tpu.runtime import cancellation

        if cancel is None:
            cancel = cancellation.current()
        wake = None
        if cancel is not None:
            cancel.check()  # fail fast before taking a ticket

            def wake():
                with self._cv:
                    self._cv.notify_all()

            cancel.on_cancel(wake)
        try:
            self._acquire(task_id, cancel)
            if _should_stall():
                # hold-and-wait widener: keep the fresh grant held
                # through a beat so concurrent legacy queries' partial
                # holds reliably overlap and the deadlock gates form
                # their cycle deterministically. Must not raise while
                # holding the fresh grant — a cancelled victim wakes
                # early (token.wait) and surfaces the cancel at the
                # caller's existing yield points.
                if cancel is not None:
                    cancel.wait(PARTIAL_HOLD_S)
                else:
                    time.sleep(PARTIAL_HOLD_S)  # srtpu-lint: disable=raw-sleep
        finally:
            if wake is not None:
                cancel.remove_on_cancel(wake)

    def _grant_locked(self, task_id: int, qid: int, permits: int):
        self._available -= permits
        self._holders[task_id] = permits
        self._held_since[task_id] = time.monotonic()
        self._holder_query[task_id] = qid
        if qid:
            self._query_tasks.setdefault(qid, set()).add(task_id)

    def _try_group_join_locked(self, task_id: int, qid: int) -> bool:
        """Atomic-group EXPANSION: a query that already holds permits
        joins its own group without ever blocking — consuming a free
        chunk when one is available and no ticket is queued ahead,
        else riding the group's hold for free. The no-block guarantee
        is what makes the query's permit set atomic: holding members
        never wait, so cross-query hold-and-wait cycles cannot form."""
        if not self._atomic_groups or not qid:
            return False
        if not self._query_tasks.get(qid):
            return False
        if self._available >= self._permits_per_task and \
                not self._queue:
            self._grant_locked(task_id, qid, self._permits_per_task)
            self.group_joins += 1
        else:
            self._grant_locked(task_id, qid, 0)
            self.group_rides += 1
        return True

    def _acquire(self, task_id: int, cancel):
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import sanitizer as _san

        qid = obs_events.effective_query_id()
        granted = False
        with self._cv:
            if task_id in self._holders:
                return
            if self._try_group_join_locked(task_id, qid):
                granted = True
            elif self._queue_empty_and_free_locked():
                # uncontended leader fast path: no ticket, no
                # sanitizer wait edge
                self._grant_locked(task_id, qid, self._permits_per_task)
                granted = True
        if granted:
            san = _san.active()
            if san is not None:
                san.acquired(_san.SEMAPHORE, qid)
            return
        self._acquire_slow(task_id, qid, cancel)

    def _queue_empty_and_free_locked(self) -> bool:
        return not self._queue and \
            self._available >= self._permits_per_task

    def _acquire_slow(self, task_id: int, qid: int, cancel):
        """Contended leader acquisition: take a ticket, register the
        sanitizer wait-for edge, park FIFO. All-or-nothing — nothing is
        held while waiting, and the grant is one whole chunk."""
        from spark_rapids_tpu.runtime import sanitizer as _san

        san = _san.active()
        wait_rec = None
        if san is not None:
            # outside _cv: edge insertion may run cycle detection and
            # cancel a victim token whose wakeup takes _cv
            wait_rec = san.begin_wait(
                _san.SEMAPHORE, qid, token=cancel,
                wake=lambda: self._notify_all())
        try:
            with self._cv:
                if task_id in self._holders:
                    return
                if self._try_group_join_locked(task_id, qid):
                    self._sanitizer_acquired(san, qid)
                    return
                ticket = next(self._ticket)
                self._queue.append(ticket)
                start = time.monotonic_ns()
                deadline = (None if self._timeout_ms <= 0
                            else time.monotonic() +
                            self._timeout_ms / 1000.0)
                try:
                    while not (self._queue[0] == ticket and
                               self._available >=
                               self._permits_per_task):
                        if wait_rec is not None:
                            wait_rec.check()  # deadlock-victim exit
                        if cancel is not None and \
                                (cancel.cancelled or cancel.expired):
                            self.cancelled_waits += 1
                            cancel.check()  # raises
                        # the query may have become a holder through a
                        # sibling while we queued: expansion never waits
                        if self._try_group_join_locked(task_id, qid):
                            self._queue.remove(ticket)
                            self._cv.notify_all()
                            self._sanitizer_acquired(san, qid)
                            return
                        wait_s: Optional[float] = None
                        if deadline is not None:
                            wait_s = deadline - time.monotonic()
                            if wait_s <= 0:
                                self.timeouts += 1
                                waited_s = (time.monotonic_ns() -
                                            start) / 1e9
                                raise SemaphoreTimeout(
                                    f"task {task_id} timed out after "
                                    f"{waited_s:.1f}s waiting for "
                                    f"{self._permits_per_task} device "
                                    f"permits ({self._available}/"
                                    f"{MAX_PERMITS} available, queue "
                                    f"position "
                                    f"{self._queue.index(ticket) + 1}/"
                                    f"{len(self._queue)}); held "
                                    f"permits: "
                                    f"{self._holder_diagnostics()}")
                        if cancel is not None:
                            r = cancel.remaining_s()
                            if r is not None:
                                r += 0.001  # wake past the deadline
                                wait_s = r if wait_s is None \
                                    else min(wait_s, r)
                        self._cv.wait(wait_s)
                except BaseException:
                    self._queue.remove(ticket)
                    # the next ticket may be eligible right now
                    self._cv.notify_all()
                    raise
                self._queue.popleft()
                self.total_wait_ns += time.monotonic_ns() - start
                self._grant_locked(task_id, qid,
                                   self._permits_per_task)
                # permits may remain for the NEW front ticket
                self._cv.notify_all()
            self._sanitizer_acquired(san, qid)
        finally:
            if wait_rec is not None:
                san.end_wait(wait_rec)

    def _notify_all(self):
        with self._cv:
            self._cv.notify_all()

    @staticmethod
    def _sanitizer_acquired(san, qid: int) -> None:
        if san is not None:
            from spark_rapids_tpu.runtime import sanitizer as _san

            san.acquired(_san.SEMAPHORE, qid)

    def _holder_diagnostics(self) -> str:
        """Under _cv: the held-permit table a timed-out acquirer dumps
        (the reference's GpuSemaphore dumpActiveStackTracesToLog
        role, scoped to what this runtime can see). Each row names the
        holder's QUERY and its elapsed hold time, so a wedged-query
        diagnosis reads off which query to session.cancel()."""
        now = time.monotonic()
        rows = [f"task={tid} query={self._holder_query.get(tid, 0)} "
                f"permits={p} "
                f"held_s={now - self._held_since.get(tid, now):.1f}"
                for tid, p in sorted(self._holders.items())]
        table = "[" + ", ".join(rows) + "]" if rows else "[none]"
        # engine fence state + device epoch (runtime/device_monitor.py):
        # a SemaphoreTimeout during device-loss recovery names the
        # fence, so the diagnosis is "recovery in progress", not a
        # mystery wedge
        from spark_rapids_tpu.runtime import device_monitor

        mon = device_monitor.get()
        state = "FENCED" if mon.fenced else "RUNNING"
        return (f"{table}; engine={state} "
                f"deviceEpoch={mon.epoch}")

    def release_if_necessary(self, task_id: int):
        from spark_rapids_tpu.runtime import sanitizer as _san

        qid = None
        with self._cv:
            permits = self._holders.pop(task_id, None)
            self._held_since.pop(task_id, None)
            qid = self._holder_query.pop(task_id, None)
            if qid:
                group = self._query_tasks.get(qid)
                if group is not None:
                    group.discard(task_id)
                    if not group:
                        del self._query_tasks[qid]
            if permits:
                self._available += permits
                self._cv.notify_all()
        if permits is not None:
            san = _san.active()
            if san is not None:
                san.released(_san.SEMAPHORE, qid or 0)

    def holders(self) -> int:
        with self._cv:
            return len(self._holders)

    def waiting(self) -> int:
        with self._cv:
            return len(self._queue)

    def query_holds(self, qid: int) -> int:
        """How many task-level holds (chunk or free-ride) query `qid`'s
        group currently has — diagnostics + tests."""
        with self._cv:
            return len(self._query_tasks.get(qid, ()))


_instance: Optional[TpuSemaphore] = None
_lock = threading.Lock()


def initialize(concurrent_tasks: int,
               acquire_timeout_ms: int = DEFAULT_ACQUIRE_TIMEOUT_MS,
               atomic_query_groups: bool = True):
    global _instance
    with _lock:
        old, _instance = _instance, TpuSemaphore(
            concurrent_tasks, acquire_timeout_ms,
            atomic_query_groups=atomic_query_groups)
    if old is not None:
        # wake anyone still blocked on the replaced instance — their
        # releases would otherwise notify only the new one, stranding
        # them on a condition variable nobody signals again
        with old._cv:
            old._available = MAX_PERMITS
            old._cv.notify_all()


def get() -> TpuSemaphore:
    global _instance
    with _lock:
        if _instance is None:
            _instance = TpuSemaphore()
        return _instance
