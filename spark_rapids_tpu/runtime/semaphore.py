"""Task-admission semaphore — the GpuSemaphore analog.

Reference (`GpuSemaphore.scala:100-421`): limits how many tasks hold
device memory concurrently; permits = 1000 / concurrentGpuTasks; tracks
wait time for task metrics. Same design: a counted semaphore keyed by
task id so re-entrant acquires are free, with wait-time accounting.

Hardened (PR 2): acquisition honors a conf'd timeout
(`spark.rapids.tpu.semaphore.acquireTimeoutMs`). A leaked permit (a
task that died without releasing) used to hang every later task
forever with zero diagnostics; now the blocked acquire raises
SemaphoreTimeout carrying the held-permit table — which task ids hold
how many permits, for how long — so the operator sees the culprit
instead of a silent wedge.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

from spark_rapids_tpu.runtime.errors import SemaphoreTimeout

MAX_PERMITS = 1000

DEFAULT_ACQUIRE_TIMEOUT_MS = 600_000


class TpuSemaphore:
    def __init__(self, concurrent_tasks: int = 2,
                 acquire_timeout_ms: int = DEFAULT_ACQUIRE_TIMEOUT_MS):
        concurrent_tasks = max(1, concurrent_tasks)
        self._permits_per_task = max(1, MAX_PERMITS // concurrent_tasks)
        self._available = MAX_PERMITS
        self._cv = threading.Condition()
        self._holders: Dict[int, int] = {}
        self._held_since: Dict[int, float] = {}
        self._timeout_ms = acquire_timeout_ms
        self.total_wait_ns = 0
        self.timeouts = 0

    def acquire_if_necessary(self, task_id: int):
        with self._cv:
            if task_id in self._holders:
                return
            start = time.monotonic_ns()
            deadline = (None if self._timeout_ms <= 0
                        else time.monotonic() + self._timeout_ms / 1000.0)
            while self._available < self._permits_per_task:
                if deadline is None:
                    self._cv.wait()
                    continue
                remaining = deadline - time.monotonic()
                if remaining > 0:
                    self._cv.wait(remaining)
                    continue  # woken or timed out: re-check permits
                self.timeouts += 1
                waited_s = (time.monotonic_ns() - start) / 1e9
                raise SemaphoreTimeout(
                    f"task {task_id} timed out after {waited_s:.1f}s "
                    f"waiting for {self._permits_per_task} device "
                    f"permits ({self._available}/{MAX_PERMITS} "
                    f"available); held permits: "
                    f"{self._holder_diagnostics()}")
            self.total_wait_ns += time.monotonic_ns() - start
            self._available -= self._permits_per_task
            self._holders[task_id] = self._permits_per_task
            self._held_since[task_id] = time.monotonic()

    def _holder_diagnostics(self) -> str:
        """Under _cv: the held-permit table a timed-out acquirer dumps
        (the reference's GpuSemaphore dumpActiveStackTracesToLog
        role, scoped to what this runtime can see)."""
        now = time.monotonic()
        rows = [f"task={tid} permits={p} "
                f"held_s={now - self._held_since.get(tid, now):.1f}"
                for tid, p in sorted(self._holders.items())]
        return "[" + ", ".join(rows) + "]" if rows else "[none]"

    def release_if_necessary(self, task_id: int):
        with self._cv:
            permits = self._holders.pop(task_id, None)
            self._held_since.pop(task_id, None)
            if permits:
                self._available += permits
                self._cv.notify_all()

    def holders(self) -> int:
        with self._cv:
            return len(self._holders)


_instance: Optional[TpuSemaphore] = None
_lock = threading.Lock()


def initialize(concurrent_tasks: int,
               acquire_timeout_ms: int = DEFAULT_ACQUIRE_TIMEOUT_MS):
    global _instance
    with _lock:
        old, _instance = _instance, TpuSemaphore(concurrent_tasks,
                                                 acquire_timeout_ms)
    if old is not None:
        # wake anyone still blocked on the replaced instance — their
        # releases would otherwise notify only the new one, stranding
        # them on a condition variable nobody signals again
        with old._cv:
            old._available = MAX_PERMITS
            old._cv.notify_all()


def get() -> TpuSemaphore:
    global _instance
    with _lock:
        if _instance is None:
            _instance = TpuSemaphore()
        return _instance
