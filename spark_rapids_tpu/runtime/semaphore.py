"""Task-admission semaphore — the GpuSemaphore analog.

Reference (`GpuSemaphore.scala:100-421`): limits how many tasks hold
device memory concurrently; permits = 1000 / concurrentGpuTasks; tracks
wait time for task metrics. Same design: a counted semaphore keyed by
task id so re-entrant acquires are free, with wait-time accounting.

Hardened (PR 2): acquisition honors a conf'd timeout
(`spark.rapids.tpu.semaphore.acquireTimeoutMs`). A leaked permit (a
task that died without releasing) used to hang every later task
forever with zero diagnostics; now the blocked acquire raises
SemaphoreTimeout carrying the held-permit table — which task ids hold
how many permits, owned by which query, for how long — so the operator
sees the culprit (and which query to `session.cancel`) instead of a
silent wedge.

Governance (PR 5):

- **FIFO fairness via ticket ordering**: waiters are served in arrival
  order. The old wake-and-race grant let a stream of late arrivals
  repeatedly slip in front of a parked waiter whenever permits freed
  (each notify_all raced every waiter plus any NEW acquirer that never
  slept) — a heavy waiter could starve indefinitely behind light
  traffic. Now every first-time acquirer takes a monotonically
  increasing ticket and only the front ticket may take permits;
  re-entrant acquires (already holding) remain free.
- **Cooperative cancellation**: an acquire under a query CancelToken
  (runtime/cancellation.py — resolved from the thread scope, or passed
  explicitly) registers a cancel wakeup and leaves the wait promptly
  when the query is cancelled or its deadline passes, removing its
  ticket so the queue never wedges behind a dead waiter.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from typing import Dict, Optional

from spark_rapids_tpu.runtime.errors import SemaphoreTimeout

MAX_PERMITS = 1000

DEFAULT_ACQUIRE_TIMEOUT_MS = 600_000


class TpuSemaphore:
    def __init__(self, concurrent_tasks: int = 2,
                 acquire_timeout_ms: int = DEFAULT_ACQUIRE_TIMEOUT_MS):
        concurrent_tasks = max(1, concurrent_tasks)
        self._permits_per_task = max(1, MAX_PERMITS // concurrent_tasks)
        self._available = MAX_PERMITS
        self._cv = threading.Condition()
        self._holders: Dict[int, int] = {}
        self._held_since: Dict[int, float] = {}
        self._holder_query: Dict[int, int] = {}
        self._queue: deque = deque()  # tickets, FIFO
        self._ticket = itertools.count(1)
        self._timeout_ms = acquire_timeout_ms
        self.total_wait_ns = 0
        self.timeouts = 0
        self.cancelled_waits = 0

    def acquire_if_necessary(self, task_id: int, cancel=None):
        from spark_rapids_tpu.runtime import cancellation

        if cancel is None:
            cancel = cancellation.current()
        wake = None
        if cancel is not None:
            cancel.check()  # fail fast before taking a ticket

            def wake():
                with self._cv:
                    self._cv.notify_all()

            cancel.on_cancel(wake)
        try:
            self._acquire(task_id, cancel)
        finally:
            if wake is not None:
                cancel.remove_on_cancel(wake)

    def _acquire(self, task_id: int, cancel):
        with self._cv:
            if task_id in self._holders:
                return
            ticket = next(self._ticket)
            self._queue.append(ticket)
            start = time.monotonic_ns()
            deadline = (None if self._timeout_ms <= 0
                        else time.monotonic() + self._timeout_ms / 1000.0)
            try:
                while not (self._queue[0] == ticket and
                           self._available >= self._permits_per_task):
                    if cancel is not None and \
                            (cancel.cancelled or cancel.expired):
                        self.cancelled_waits += 1
                        cancel.check()  # raises
                    wait_s: Optional[float] = None
                    if deadline is not None:
                        wait_s = deadline - time.monotonic()
                        if wait_s <= 0:
                            self.timeouts += 1
                            waited_s = (time.monotonic_ns() - start) / 1e9
                            raise SemaphoreTimeout(
                                f"task {task_id} timed out after "
                                f"{waited_s:.1f}s waiting for "
                                f"{self._permits_per_task} device "
                                f"permits ({self._available}/"
                                f"{MAX_PERMITS} available, queue "
                                f"position "
                                f"{self._queue.index(ticket) + 1}/"
                                f"{len(self._queue)}); held permits: "
                                f"{self._holder_diagnostics()}")
                    if cancel is not None:
                        r = cancel.remaining_s()
                        if r is not None:
                            r += 0.001  # wake just past the deadline
                            wait_s = r if wait_s is None \
                                else min(wait_s, r)
                    self._cv.wait(wait_s)
            except BaseException:
                self._queue.remove(ticket)
                # the next ticket may be eligible right now
                self._cv.notify_all()
                raise
            self._queue.popleft()
            self.total_wait_ns += time.monotonic_ns() - start
            self._available -= self._permits_per_task
            self._holders[task_id] = self._permits_per_task
            self._held_since[task_id] = time.monotonic()
            from spark_rapids_tpu.obs import events as obs_events

            self._holder_query[task_id] = obs_events.effective_query_id()
            # permits may remain for the NEW front ticket
            self._cv.notify_all()

    def _holder_diagnostics(self) -> str:
        """Under _cv: the held-permit table a timed-out acquirer dumps
        (the reference's GpuSemaphore dumpActiveStackTracesToLog
        role, scoped to what this runtime can see). Each row names the
        holder's QUERY and its elapsed hold time, so a wedged-query
        diagnosis reads off which query to session.cancel()."""
        now = time.monotonic()
        rows = [f"task={tid} query={self._holder_query.get(tid, 0)} "
                f"permits={p} "
                f"held_s={now - self._held_since.get(tid, now):.1f}"
                for tid, p in sorted(self._holders.items())]
        return "[" + ", ".join(rows) + "]" if rows else "[none]"

    def release_if_necessary(self, task_id: int):
        with self._cv:
            permits = self._holders.pop(task_id, None)
            self._held_since.pop(task_id, None)
            self._holder_query.pop(task_id, None)
            if permits:
                self._available += permits
                self._cv.notify_all()

    def holders(self) -> int:
        with self._cv:
            return len(self._holders)

    def waiting(self) -> int:
        with self._cv:
            return len(self._queue)


_instance: Optional[TpuSemaphore] = None
_lock = threading.Lock()


def initialize(concurrent_tasks: int,
               acquire_timeout_ms: int = DEFAULT_ACQUIRE_TIMEOUT_MS):
    global _instance
    with _lock:
        old, _instance = _instance, TpuSemaphore(concurrent_tasks,
                                                 acquire_timeout_ms)
    if old is not None:
        # wake anyone still blocked on the replaced instance — their
        # releases would otherwise notify only the new one, stranding
        # them on a condition variable nobody signals again
        with old._cv:
            old._available = MAX_PERMITS
            old._cv.notify_all()


def get() -> TpuSemaphore:
    global _instance
    with _lock:
        if _instance is None:
            _instance = TpuSemaphore()
        return _instance
