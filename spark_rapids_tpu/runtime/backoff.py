"""Bounded exponential backoff with jitter — the shared recovery policy
for transient I/O failure domains (shuffle block fetch/decode, file
reads, disk-tier spill). Distributed engines treat data-movement
failures as normal events to be retried before anything escalates
(Theseus, PAPERS.md); here every retryable site funnels through ONE
policy so the attempt budget and delay curve are conf'd once
(`spark.rapids.tpu.io.retry.*`) and counted once.

`retry_io` also carries the chaos harness: when a fault-injection site
is named, each ATTEMPT first asks the registry (runtime/faults.py) to
inject — so the backoff loop is itself the code under test, and an
injected fault is recovered exactly like a real one. Injected faults
raised by a DIFFERENT site deeper in `fn` propagate untouched: each
site's consumer must survive its own faults, not its callees'.
"""

from __future__ import annotations

import random
import threading
import time
from collections import defaultdict
from typing import Callable, Dict, Optional, Tuple, TypeVar

from spark_rapids_tpu.runtime.errors import RetryExhausted
from spark_rapids_tpu.runtime.faults import InjectedFault

T = TypeVar("T")

_counters: Dict[str, int] = defaultdict(int)
_counters_lock = threading.Lock()
# jitter decorrelates concurrent retriers; seeded so runs are
# reproducible enough for the chaos gate's wall-clock budget
_jitter_rng = random.Random(0x5EED)


class BackoffPolicy:
    """attempts total tries; delay_i = min(max, base * 2^i) * jitter,
    jitter uniform in [0.5, 1.0] (full-jitter halves herd alignment
    without ever sleeping longer than the exponential envelope)."""

    __slots__ = ("attempts", "base_ms", "max_ms")

    def __init__(self, attempts: int = 4, base_ms: float = 50.0,
                 max_ms: float = 2000.0):
        self.attempts = max(1, int(attempts))
        self.base_ms = float(base_ms)
        self.max_ms = float(max_ms)

    def delay_s(self, attempt: int) -> float:
        raw = min(self.max_ms, self.base_ms * (2 ** attempt))
        return raw / 1000.0 * (0.5 + 0.5 * _jitter_rng.random())


def total_budget_ms(conf=None) -> int:
    """The per-query cumulative retry-delay budget
    (spark.rapids.tpu.io.retry.maxTotalMs; 0 = unlimited), resolved
    like the policy: session conf first, entry default otherwise."""
    from spark_rapids_tpu.config import rapids_conf as rc

    if conf is None:
        from spark_rapids_tpu.api.session import TpuSparkSession

        s = TpuSparkSession.active()
        conf = s.rapids_conf if s is not None else None
    if conf is None:
        return int(rc.IO_RETRY_MAX_TOTAL_MS.default)
    return int(conf.get(rc.IO_RETRY_MAX_TOTAL_MS))


def policy_from_conf(conf=None) -> BackoffPolicy:
    """Resolve the session's retry policy (falls back to entry defaults
    when no session is active — component-level callers and tests)."""
    from spark_rapids_tpu.config import rapids_conf as rc

    if conf is None:
        from spark_rapids_tpu.api.session import TpuSparkSession

        s = TpuSparkSession.active()
        conf = s.rapids_conf if s is not None else None
    if conf is None:
        return BackoffPolicy(rc.IO_RETRY_ATTEMPTS.default,
                             rc.IO_RETRY_BACKOFF_MS.default,
                             rc.IO_RETRY_MAX_BACKOFF_MS.default)
    return BackoffPolicy(conf.get(rc.IO_RETRY_ATTEMPTS),
                         conf.get(rc.IO_RETRY_BACKOFF_MS),
                         conf.get(rc.IO_RETRY_MAX_BACKOFF_MS))


def retry_io(fn: Callable[[], T], what: str,
             site: Optional[str] = None,
             retry_on: Tuple[type, ...] = (OSError,),
             no_retry: Tuple[type, ...] = (),
             absorb_sites: Tuple[str, ...] = (),
             policy: Optional[BackoffPolicy] = None,
             counter: Optional[str] = None,
             on_retry: Optional[Callable[[BaseException], None]] = None,
             sleep: Optional[Callable[[float], None]] = None) -> T:
    """Run `fn` under the backoff policy. Exceptions in `retry_on` (or
    an InjectedFault for `site` / one of `absorb_sites` — sites whose
    recovery point is THIS loop, e.g. shuffle.deserialize faults
    surfacing inside a shuffle.fetch retry) consume an attempt;
    `no_retry` classes fail immediately (a missing file is not
    transient). The final failure raises RetryExhausted chained to the
    last error — callers convert it to their domain's clean engine
    error."""
    from spark_rapids_tpu.runtime import cancellation, faults

    policy = policy or policy_from_conf()
    # default sleep is cancellation-aware: a cancelled query leaves the
    # backoff loop at the next delay instead of riding it out (callers
    # passing their own sleep — tests — keep full control)
    if sleep is None:
        sleep = cancellation.sleep_interruptible
    mine = tuple(s for s in ((site,) + tuple(absorb_sites)) if s)
    last: Optional[BaseException] = None
    for attempt in range(policy.attempts):
        cancellation.check_current()
        try:
            if site is not None:
                faults.maybe_inject(site, detail=what)
            return fn()
        except no_retry:
            raise
        except InjectedFault as e:
            if e.site not in mine:
                raise  # a different site's fault: not ours to absorb
            last = e
        except retry_on as e:
            last = e
        key = counter or site or "io"
        with _counters_lock:
            _counters[key] += 1
        if on_retry is not None:
            on_retry(last)
        if attempt < policy.attempts - 1:
            delay_s = policy.delay_s(attempt)
            # per-QUERY cumulative budget: chained retry storms (every
            # site backing off at once during a device outage) fail
            # fast with the budget named, instead of multiplying
            # per-site backoffs into minutes of stacked sleeps
            token = cancellation.current()
            if token is not None:
                budget = total_budget_ms()
                if budget > 0:
                    used = token.charge_retry_ms(delay_s * 1000.0)
                    if used > budget:
                        raise RetryExhausted(
                            f"{what}: per-query cumulative retry "
                            f"budget spark.rapids.tpu.io.retry."
                            f"maxTotalMs={budget} exhausted "
                            f"({used:.0f}ms of backoff across this "
                            f"query's retry sites; last: "
                            f"{type(last).__name__}: {last})"
                        ) from last
            sleep(delay_s)
    raise RetryExhausted(
        f"{what}: {policy.attempts} attempts exhausted "
        f"(last: {type(last).__name__}: {last})") from last


def record_retry(key: str, n: int = 1) -> None:
    """Count a retry attempt made OUTSIDE retry_io (e.g. ServeClient's
    connect loop, the fleet router's failover resubmits) under the same
    counters surface, so obs snapshots see every backoff consumer."""
    with _counters_lock:
        _counters[key] += int(n)


def counters() -> Dict[str, int]:
    with _counters_lock:
        return dict(_counters)


def reset_counters() -> None:
    with _counters_lock:
        _counters.clear()
