"""Generator expressions (explode/posexplode) — markers consumed by the
planner's Generate conversion (GpuGenerateExec analog,
GpuGenerateExec.scala). A generator never evaluates inline: the
DataFrame layer extracts it from a projection into an L.Generate node,
like Spark's ExtractGenerator analysis rule."""

from __future__ import annotations

from spark_rapids_tpu.expr.core import Expression


class Explode(Expression):
    """explode(array): one output row per (non-null) array element."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype.elementType

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        raise RuntimeError(
            "generator expressions are planned as Generate nodes, "
            "never evaluated inline")

    def key(self):
        return ("explode", self.children[0].key())


class PosExplode(Explode):
    """posexplode(array): (pos, col) rows."""

    def key(self):
        return ("posexplode", self.children[0].key())


def contains_generator(e: Expression) -> bool:
    if isinstance(e, Explode):
        return True
    return any(contains_generator(c) for c in e.children)
