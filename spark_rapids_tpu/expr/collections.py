"""Collection expressions over the padded-matrix array layout — the
collectionOperations.scala analog (reference: GpuSize, GpuArrayContains,
GpuElementAt/GetArrayItem, GpuCreateArray; cuDF list kernels).

All device evals are vectorized jnp over [cap, max_elems] matrices; null
semantics follow Spark:
- size(null) = -1 (legacy sizeOfNull=true default),
- array_contains: null if the array is null; true if found; null if not
  found but the array has null elements; else false,
- getItem / element_at out of bounds -> null (non-ANSI),
- array(...) of N children builds a fixed-N array per row.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression, Literal
from spark_rapids_tpu.sqltypes import ArrayType, IntegerType
from spark_rapids_tpu.sqltypes.datatypes import boolean, integer


class Size(Expression):
    """size(array): element count; -1 for null (Spark legacy default)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        n = c.lengths.astype(jnp.int32)
        data = jnp.where(c.validity, n, jnp.int32(-1))
        return DeviceColumn(integer, data,
                            jnp.ones(data.shape, bool))


class ArrayContains(Expression):
    def __init__(self, arr: Expression, value: Expression):
        super().__init__([arr, value])

    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        v = self.children[1].eval(ctx)
        me = c.data.shape[1]
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :] <
                  c.lengths[:, None])
        elem_ok = in_row & c.elem_validity
        if c.elem_lengths is not None:  # array<string> needle compare
            nb = v.data.shape[1]
            eb = c.data.shape[2]
            w = max(nb, eb)
            elems = jnp.pad(c.data, ((0, 0), (0, 0), (0, w - eb)))
            needle = jnp.pad(v.data, ((0, 0), (0, w - nb)))
            eq = (jnp.all(elems == needle[:, None, :], axis=2) &
                  (c.elem_lengths == v.lengths[:, None]))
        else:
            eq = c.data == v.data[:, None]
        hit = jnp.any(elem_ok & eq, axis=1)
        has_null_elem = jnp.any(in_row & ~c.elem_validity, axis=1)
        valid = c.validity & v.validity & (hit | ~has_null_elem)
        return DeviceColumn(boolean, hit, valid)


class GetArrayItem(Expression):
    """array[index]; out-of-bounds or null element -> null."""

    def __init__(self, arr: Expression, index: Expression):
        super().__init__([arr, index])

    @property
    def dtype(self):
        return self.children[0].dtype.elementType

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        i = self.children[1].eval(ctx)
        idx = i.data.astype(jnp.int32)
        in_bounds = (idx >= 0) & (idx < c.lengths)
        safe = jnp.clip(idx, 0, c.data.shape[1] - 1)
        ev = jnp.take_along_axis(c.elem_validity,
                                 safe[:, None].astype(jnp.int64),
                                 axis=1)[:, 0]
        valid = c.validity & i.validity & in_bounds & ev
        if c.elem_lengths is not None:  # array<string> -> string col
            rows = jnp.arange(c.capacity)
            return DeviceColumn(self.dtype, c.data[rows, safe], valid,
                                c.elem_lengths[rows, safe])
        vals = jnp.take_along_axis(c.data, safe[:, None].astype(jnp.int64),
                                   axis=1)[:, 0]
        return DeviceColumn(self.dtype, vals, valid)


class ElementAt(Expression):
    """element_at(array, i): 1-based, negative counts from the end;
    element_at(map, key): value lookup (GetMapValue semantics)."""

    def __init__(self, arr: Expression, index: Expression):
        super().__init__([arr, index])

    @property
    def _is_map(self):
        from spark_rapids_tpu.sqltypes import MapType

        return isinstance(self.children[0].dtype, MapType)

    @property
    def dtype(self):
        if self._is_map:
            return self.children[0].dtype.valueType
        return self.children[0].dtype.elementType

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        if self._is_map:
            return GetMapValue(*self.children).eval(ctx)
        c = self.children[0].eval(ctx)
        i = self.children[1].eval(ctx)
        raw = i.data.astype(jnp.int32)
        idx = jnp.where(raw > 0, raw - 1, c.lengths + raw)
        in_bounds = (idx >= 0) & (idx < c.lengths) & (raw != 0)
        safe = jnp.clip(idx, 0, c.data.shape[1] - 1)
        ev = jnp.take_along_axis(c.elem_validity,
                                 safe[:, None].astype(jnp.int64),
                                 axis=1)[:, 0]
        valid = c.validity & i.validity & in_bounds & ev
        if c.elem_lengths is not None:  # array<string> -> string col
            rows = jnp.arange(c.capacity)
            return DeviceColumn(self.dtype, c.data[rows, safe], valid,
                                c.elem_lengths[rows, safe])
        vals = jnp.take_along_axis(c.data, safe[:, None].astype(jnp.int64),
                                   axis=1)[:, 0]
        return DeviceColumn(self.dtype, vals, valid)


class CreateArray(Expression):
    """array(e1, ..., eN): fixed-width array per row."""

    def __init__(self, *children: Expression):
        super().__init__(list(children))

    @property
    def dtype(self):
        et = (self.children[0].dtype if self.children
              else IntegerType())
        return ArrayType(et)

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        if not self.children:
            cap = ctx.capacity
            return DeviceColumn(
                self.dtype,
                jnp.zeros((cap, 1), self.dtype.elementType.np_dtype),
                jnp.ones((cap,), bool),
                jnp.zeros((cap,), jnp.int32),
                jnp.zeros((cap, 1), bool))
        cols = [c.eval(ctx) for c in self.children]
        n = len(cols)
        data = jnp.stack([c.data for c in cols], axis=1)
        ev = jnp.stack([c.validity for c in cols], axis=1)
        cap = data.shape[0]
        lengths = jnp.full((cap,), jnp.int32(n))
        return DeviceColumn(self.dtype, data,
                            jnp.ones((cap,), bool), lengths, ev)


# ----------------------- higher-order functions (higherOrderFunctions.scala)

class LambdaVar(Expression):
    """Element placeholder inside an array lambda; eval reads the bound
    flattened element column off the context (set by the enclosing
    higher-order expression)."""

    _SLOT = "_lambda_elem"

    def __init__(self, dtype):
        super().__init__()
        self._dtype = dtype

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        col = getattr(ctx, self._SLOT, None)
        if col is None:
            raise RuntimeError("lambda variable outside a lambda")
        return col

    def key(self):
        return ("lambda_var", repr(self._dtype))


def _flat_elems(c: DeviceColumn) -> DeviceColumn:
    """[cap, me] array column -> flattened [cap*me] element column."""
    return DeviceColumn(c.dtype.elementType, c.data.reshape(-1),
                        c.elem_validity.reshape(-1))


def _eval_lambda(lam: Expression, c: DeviceColumn) -> DeviceColumn:
    """Evaluate the lambda tree over the flattened elements in a
    context sized [cap*me] (literals/etc. broadcast to element count,
    not row count)."""
    from spark_rapids_tpu.columnar.batch import ColumnBatch
    from spark_rapids_tpu.expr.core import EvalContext
    from spark_rapids_tpu.sqltypes import StructField, StructType

    flat = _flat_elems(c)
    fb = ColumnBatch(StructType([StructField("x", flat.dtype, True)]),
                     [flat], int(c.data.size))
    fctx = EvalContext(fb)
    setattr(fctx, LambdaVar._SLOT, flat)
    return lam.eval(fctx)


class _HigherOrder(Expression):
    """Shared deferred-lambda machinery: the user's python fn builds the
    lambda expression tree once the array child resolves to a concrete
    ArrayType (Column resolution calls with_children bottom-up)."""

    def __init__(self, arr: Expression, lam: Expression = None,
                 fn=None):
        children = [arr] + ([lam] if lam is not None else [])
        super().__init__(children)
        self.fn = fn
        if lam is None and fn is not None:
            self._try_build()

    def _try_build(self):
        arr = self.children[0]
        try:
            at = arr.dtype
        except Exception:
            return
        if isinstance(at, ArrayType):
            from spark_rapids_tpu.api.column import Column

            var = LambdaVar(at.elementType)
            lam_col = self.fn(Column(var, "x"))
            lam = lam_col.expr if hasattr(lam_col, "expr") else lam_col
            if lam.references():
                raise ValueError(
                    "array lambdas may reference only the element in v1")
            self.children.append(lam)

    def with_children(self, children):
        node = type(self)(children[0],
                          children[1] if len(children) > 1 else None,
                          fn=self.fn)
        if len(node.children) == 1 and node.fn is not None:
            node._try_build()
        return node

    @property
    def _lam(self):
        if len(self.children) < 2:
            raise RuntimeError(
                "higher-order lambda unresolved (array child has no "
                "concrete type yet)")
        return self.children[1]


class ArrayTransform(_HigherOrder):
    """transform(arr, x -> f(x)) evaluated ON DEVICE: the lambda's
    scalar expression tree runs elementwise over the flattened element
    matrix — XLA fuses it with the rest of the projection (the
    reference needs cuDF transform kernels per lambda;
    higherOrderFunctions.scala)."""

    @property
    def dtype(self):
        return ArrayType(self._lam.dtype)

    @property
    def nullable(self):
        return self.children[0].nullable

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        cap, me = c.data.shape
        out = _eval_lambda(self._lam, c)
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :] <
                  c.lengths[:, None])
        data = out.data.reshape(cap, me)
        ev = out.validity.reshape(cap, me) & in_row
        return DeviceColumn(self.dtype, data, c.validity, c.lengths, ev)


class ArrayFilter(_HigherOrder):
    """filter(arr, x -> pred(x)): keeps elements where the predicate is
    true, compacting within each row (stable argsort on the keep mask)."""

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return self.children[0].nullable

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        cap, me = c.data.shape
        pred = _eval_lambda(self._lam, c)
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :] <
                  c.lengths[:, None])
        keep = pred.data.reshape(cap, me) & pred.validity.reshape(
            cap, me) & in_row
        return _row_compact(self.dtype, c.data, c.elem_validity, keep,
                            c.validity)


class _ArrayReduce(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype.elementType

    @property
    def nullable(self):
        return True

    def _mask(self, c):
        me = c.data.shape[1]
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :] <
                  c.lengths[:, None])
        return in_row & c.elem_validity


class ArrayMax(_ArrayReduce):
    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        ok = self._mask(c)
        if jnp.issubdtype(c.data.dtype, jnp.floating):
            ident = jnp.array(-jnp.inf, c.data.dtype)
        else:
            ident = jnp.array(jnp.iinfo(c.data.dtype).min, c.data.dtype)
        vals = jnp.max(jnp.where(ok, c.data, ident), axis=1)
        valid = c.validity & jnp.any(ok, axis=1)
        return DeviceColumn(self.dtype, vals, valid)


class ArrayMin(_ArrayReduce):
    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        ok = self._mask(c)
        if jnp.issubdtype(c.data.dtype, jnp.floating):
            # Spark orders NaN greatest: the min is the smallest non-NaN
            # value, NaN only when every element is NaN
            data = jnp.where(jnp.isnan(c.data), jnp.inf, c.data)
            vals = jnp.min(jnp.where(ok, data, jnp.inf), axis=1)
            all_nan = ~jnp.any(ok & ~jnp.isnan(c.data), axis=1)
            vals = jnp.where(all_nan & jnp.any(ok, axis=1), jnp.nan,
                             vals)
        else:
            ident = jnp.array(jnp.iinfo(c.data.dtype).max, c.data.dtype)
            vals = jnp.min(jnp.where(ok, c.data, ident), axis=1)
        valid = c.validity & jnp.any(ok, axis=1)
        return DeviceColumn(self.dtype, vals, valid)


class SortArray(Expression):
    """sort_array(arr, asc): per-row element sort; nulls first for
    ascending, last for descending (Spark semantics)."""

    def __init__(self, child: Expression, ascending: bool = True):
        super().__init__([child])
        self.ascending = ascending

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return self.children[0].nullable

    def key(self):
        return ("sort_array", self.ascending, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        me = c.data.shape[1]
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :] <
                  c.lengths[:, None])
        # rank: dead slots always last; nulls first (asc) / last (desc)
        if self.ascending:
            rank = jnp.where(in_row & c.elem_validity, 1,
                             jnp.where(in_row, 0, 2))
        else:
            rank = jnp.where(in_row & c.elem_validity, 0,
                             jnp.where(in_row, 1, 2))
        key = c.data
        if jnp.issubdtype(key.dtype, jnp.bool_):
            key = key.astype(jnp.int32)
        if jnp.issubdtype(key.dtype, jnp.floating):
            key = jnp.where(jnp.isnan(key), jnp.inf, key)
        if not self.ascending:
            key = -key
        order = jnp.lexsort((key, rank), axis=1)
        data = jnp.take_along_axis(c.data, order, axis=1)
        ev = jnp.take_along_axis(c.elem_validity, order, axis=1)
        return DeviceColumn(self.dtype, data, c.validity, c.lengths, ev)


# -------------------------------------------------------------- maps
#
# Map functions (reference collectionOperations.scala map rules +
# complexTypeExtractors GetMapValue): device layout keeps keys in the
# column's data matrix and values in map_values (sqltypes MapType).


class MapKeys(Expression):
    """map_keys(m) -> array<k>."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes import ArrayType

        return ArrayType(self.children[0].dtype.keyType, False)

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        me = c.data.shape[1]
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :]
                  < c.lengths[:, None])
        return DeviceColumn(self.dtype, c.data, c.validity, c.lengths,
                            in_row)


class MapValues(Expression):
    """map_values(m) -> array<v>."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes import ArrayType

        mt = self.children[0].dtype
        return ArrayType(mt.valueType, mt.valueContainsNull)

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(self.dtype, c.map_values, c.validity,
                            c.lengths, c.elem_validity)


class MapContainsKey(Expression):
    def __init__(self, m: Expression, key: Expression):
        super().__init__([m, key])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import boolean

        return boolean

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import binary_validity
        from spark_rapids_tpu.sqltypes.datatypes import boolean

        c = self.children[0].eval(ctx)
        k = self.children[1].eval(ctx)
        me = c.data.shape[1]
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :]
                  < c.lengths[:, None])
        hit = in_row & (c.data == k.data[:, None])
        return DeviceColumn(boolean, hit.any(axis=1),
                            binary_validity(c, k))


class GetMapValue(Expression):
    """m[key] / element_at(m, key): first matching key's value, null
    when absent (GetMapValue non-ANSI semantics)."""

    def __init__(self, m: Expression, key: Expression):
        super().__init__([m, key])

    @property
    def dtype(self):
        return self.children[0].dtype.valueType

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        k = self.children[1].eval(ctx)
        me = c.data.shape[1]
        in_row = (jnp.arange(me, dtype=jnp.int32)[None, :]
                  < c.lengths[:, None])
        hit = in_row & (c.data == k.data[:, None])
        # first match position (me when absent)
        pos = jnp.where(hit, jnp.arange(me, dtype=jnp.int32)[None, :],
                        me).min(axis=1)
        found = pos < me
        safe = jnp.clip(pos, 0, me - 1).astype(jnp.int64)
        vals = jnp.take_along_axis(c.map_values, safe[:, None],
                                   axis=1)[:, 0]
        vv = jnp.take_along_axis(c.elem_validity, safe[:, None],
                                 axis=1)[:, 0]
        valid = c.validity & k.validity & found & vv
        return DeviceColumn(self.dtype, vals, valid)


class MapFromArrays(Expression):
    """map_from_arrays(keys_array, values_array)."""

    def __init__(self, keys: Expression, values: Expression):
        super().__init__([keys, values])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes import MapType

        ka = self.children[0].dtype
        va = self.children[1].dtype
        return MapType(ka.elementType, va.elementType)

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import binary_validity

        ka = self.children[0].eval(ctx)
        va = self.children[1].eval(ctx)
        me = max(ka.data.shape[1], va.data.shape[1])

        def pad(m):
            return jnp.pad(m, ((0, 0), (0, me - m.shape[1])))

        kd, vd = pad(ka.data), pad(va.data)
        vv = pad(va.elem_validity)
        # Spark errors on length mismatch / null keys (NULL_MAP_KEY);
        # the non-ANSI engine nulls the row instead
        same = ka.lengths == va.lengths
        me_k = ka.data.shape[1]
        in_row = (jnp.arange(me_k, dtype=jnp.int32)[None, :]
                  < ka.lengths[:, None])
        keys_ok = (~in_row | ka.elem_validity).all(axis=1)
        return DeviceColumn(self.dtype, kd,
                            binary_validity(ka, va) & same & keys_ok,
                            ka.lengths, vv, vd)


class CreateMap(Expression):
    """map(k1, v1, k2, v2, ...) from scalar columns."""

    def __init__(self, *kv: Expression):
        assert kv and len(kv) % 2 == 0, "map() needs key/value pairs"
        super().__init__(list(kv))

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes import MapType

        return MapType(self.children[0].dtype, self.children[1].dtype)

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        keys = cols[0::2]
        vals = cols[1::2]
        me = len(keys)
        kd = jnp.stack([k.data for k in keys], axis=1)
        vd = jnp.stack([v.data for v in vals], axis=1)
        vv = jnp.stack([v.validity for v in vals], axis=1)
        n = kd.shape[0]
        lengths = jnp.full((n,), jnp.int32(me))
        # a null KEY is illegal in Spark; non-ANSI: null out the row
        kvalid = jnp.stack([k.validity for k in keys], axis=1).all(axis=1)
        return DeviceColumn(self.dtype, kd, kvalid, lengths, vv, vd)


# ------------------------------------------------- array breadth (v2)
#
# Reference: collectionOperations.scala rules (slice, array_position,
# array_remove, array_distinct, reverse, exists/forall, set ops,
# concat-of-arrays, arrays_overlap). Device idiom throughout: padded
# [cap, max_elems] matrices, per-row compaction via stable argsort.


def _in_row_mask(c: DeviceColumn):
    me = c.data.shape[1]
    return (jnp.arange(me, dtype=jnp.int32)[None, :]
            < c.lengths[:, None])


def _row_compact(c_dtype, data, ev, keep, validity):
    """Keep flagged elements, left-compacted, preserving order."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(data, order, axis=1)
    oev = jnp.take_along_axis(ev & keep, order, axis=1)
    lengths = jnp.sum(keep, axis=1).astype(jnp.int32)
    return DeviceColumn(c_dtype, out, validity, lengths, oev)


def _elem_eq(a, b, a_ok=None, b_ok=None):
    """Pairwise element equality with NULL==NULL set semantics and
    NaN==NaN."""
    eq = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        eq = eq | (jnp.isnan(a) & jnp.isnan(b))
    if a_ok is not None:
        eq = (eq & a_ok & b_ok) | (~a_ok & ~b_ok)
    return eq


class Slice(Expression):
    """slice(arr, start, length): 1-based; negative start counts from
    the end; start=0 -> null row (non-ANSI)."""

    def __init__(self, arr: Expression, start: Expression,
                 length: Expression):
        super().__init__([arr, start, length])

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        st = self.children[1].eval(ctx)
        ln = self.children[2].eval(ctx)
        me = c.data.shape[1]
        raw = st.data.astype(jnp.int32)
        begin = jnp.where(raw > 0, raw - 1, c.lengths + raw)
        want = jnp.clip(ln.data.astype(jnp.int32), 0, me)
        j = jnp.arange(me, dtype=jnp.int32)[None, :]
        src = begin[:, None] + j
        # begin < 0 (|start| > length) -> empty result, NOT a partial
        # window: a plain src >= 0 test would leave holes mid-row
        inside = ((j < want[:, None]) & (begin >= 0)[:, None]
                  & (src < c.lengths[:, None]))
        safe = jnp.clip(src, 0, me - 1).astype(jnp.int64)
        data = jnp.take_along_axis(c.data, safe, axis=1)
        ev = jnp.take_along_axis(c.elem_validity, safe, axis=1) & inside
        lengths = jnp.sum(inside, axis=1).astype(jnp.int32)
        bad = (raw == 0) | (ln.data < 0)
        valid = c.validity & st.validity & ln.validity & ~bad
        return DeviceColumn(self.dtype, data, valid, lengths, ev)


class ArrayPosition(Expression):
    """array_position(arr, v): 1-based first index, 0 when absent."""

    def __init__(self, arr: Expression, value: Expression):
        super().__init__([arr, value])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import long

        return long

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import binary_validity
        from spark_rapids_tpu.sqltypes.datatypes import long

        c = self.children[0].eval(ctx)
        v = self.children[1].eval(ctx)
        me = c.data.shape[1]
        hit = (_in_row_mask(c) & c.elem_validity
               & _elem_eq(c.data, v.data[:, None]))
        pos = jnp.where(hit, jnp.arange(me, dtype=jnp.int64)[None, :],
                        me).min(axis=1)
        out = jnp.where(pos < me, pos + 1, 0)
        return DeviceColumn(long, out, binary_validity(c, v))


class ArrayRemove(Expression):
    """array_remove(arr, v); v null -> null result (Spark)."""

    def __init__(self, arr: Expression, value: Expression):
        super().__init__([arr, value])

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        v = self.children[1].eval(ctx)
        keep = _in_row_mask(c) & ~(
            c.elem_validity & _elem_eq(c.data, v.data[:, None]))
        out = _row_compact(self.dtype, c.data, c.elem_validity, keep,
                           c.validity & v.validity)
        return out


class ArrayDistinct(Expression):
    """array_distinct(arr): first occurrences, original order;
    NULL==NULL and NaN==NaN dedupe."""

    def __init__(self, arr: Expression):
        super().__init__([arr])

    @property
    def dtype(self):
        return self.children[0].dtype

    def eval(self, ctx):
        return _distinct_of(self.children[0].eval(ctx))


class Reverse(Expression):
    """reverse(array) / reverse(string)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def eval(self, ctx):
        from spark_rapids_tpu.sqltypes import StringType

        if isinstance(self.children[0].dtype, StringType):
            # character-aware (UTF-8) reverse, NOT byte reverse
            from spark_rapids_tpu.expr.strings import StringReverse

            return StringReverse(self.children[0]).eval(ctx)
        c = self.children[0].eval(ctx)
        me = c.data.shape[1]
        j = jnp.arange(me, dtype=jnp.int32)[None, :]
        src = jnp.clip(c.lengths[:, None] - 1 - j, 0, me - 1) \
            .astype(jnp.int64)
        in_row = j < c.lengths[:, None]
        data = jnp.where(in_row,
                         jnp.take_along_axis(c.data, src, axis=1), 0)
        ev = jnp.where(in_row, jnp.take_along_axis(
            c.elem_validity, src, axis=1), False)
        return DeviceColumn(self.dtype, data, c.validity, c.lengths, ev)


class ArrayExists(_HigherOrder):
    """exists(arr, x -> pred): 3-valued (any true -> true; else any
    null -> null; else false)."""

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import boolean

        return boolean

    @property
    def nullable(self):
        return True

    _forall = False

    def eval(self, ctx):
        from spark_rapids_tpu.sqltypes.datatypes import boolean

        c = self.children[0].eval(ctx)
        cap, me = c.data.shape
        pred = _eval_lambda(self._lam, c)
        in_row = _in_row_mask(c)
        # the lambda sees NULL elements (Spark evaluates it over them:
        # exists(a, x -> isnull(x)) can decide on a null entry); only
        # the PREDICATE's own null-ness makes a slot undecided
        pv = pred.data.reshape(cap, me)
        pok = pred.validity.reshape(cap, me) & in_row
        if self._forall:
            decided = (pok & ~pv).any(axis=1)   # a definite false
            result = ~decided
        else:
            decided = (pok & pv).any(axis=1)    # a definite true
            result = decided
        has_null_verdict = (in_row & ~pred.validity.reshape(cap, me)
                            ).any(axis=1)
        valid = c.validity & (decided | ~has_null_verdict)
        return DeviceColumn(boolean, result, valid)


class ArrayForall(ArrayExists):
    _forall = True


class ConcatArrays(Expression):
    """concat(arr1, arr2, ...) for array inputs."""

    def __init__(self, *arrs: Expression):
        super().__init__(list(arrs))

    @property
    def dtype(self):
        return self.children[0].dtype

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        me_out = sum(c.data.shape[1] for c in cols)
        cap = cols[0].data.shape[0]
        data = jnp.zeros((cap, me_out), cols[0].data.dtype)
        ev = jnp.zeros((cap, me_out), bool)
        offset = jnp.zeros((cap,), jnp.int32)
        for c in cols:
            me = c.data.shape[1]
            j = jnp.arange(me, dtype=jnp.int32)[None, :]
            dest = offset[:, None] + j
            inside = j < c.lengths[:, None]
            dest_safe = jnp.where(inside, dest, me_out)
            rows = jnp.broadcast_to(
                jnp.arange(cap)[:, None], (cap, me))
            data = data.at[rows, dest_safe].set(
                c.data, mode="drop")
            ev = ev.at[rows, dest_safe].set(
                c.elem_validity & inside, mode="drop")
            offset = offset + c.lengths
        valid = cols[0].validity
        for c in cols[1:]:
            valid = valid & c.validity
        return DeviceColumn(self.dtype, data, valid,
                            offset.astype(jnp.int32), ev)


class _ArraySetOp(Expression):
    """Pairwise-membership set ops (array_union/intersect/except,
    arrays_overlap) with NULL==NULL semantics."""

    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self):
        return self.children[0].dtype

    def _membership(self, a: DeviceColumn, b: DeviceColumn):
        """[cap, me_a] mask: element of a present in b."""
        eq = _elem_eq(a.data[:, :, None], b.data[:, None, :],
                      a.elem_validity[:, :, None],
                      b.elem_validity[:, None, :])
        both = (_in_row_mask(a)[:, :, None]
                & _in_row_mask(b)[:, None, :])
        return (eq & both).any(axis=2)


class ArraysOverlap(_ArraySetOp):
    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import boolean

        return boolean

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import binary_validity
        from spark_rapids_tpu.sqltypes.datatypes import boolean

        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        # Spark: true if any NON-NULL common element; null result when
        # no common element but either side has a null element
        eq = _elem_eq(a.data[:, :, None], b.data[:, None, :])
        both_ok = (a.elem_validity[:, :, None]
                   & b.elem_validity[:, None, :])
        both = (_in_row_mask(a)[:, :, None]
                & _in_row_mask(b)[:, None, :])
        overlap = (eq & both_ok & both).any(axis=(1, 2))
        has_null = ((_in_row_mask(a) & ~a.elem_validity).any(axis=1)
                    | (_in_row_mask(b) & ~b.elem_validity).any(axis=1))
        nonempty = (a.lengths > 0) & (b.lengths > 0)
        valid = binary_validity(a, b) & (
            overlap | ~(has_null & nonempty))
        return DeviceColumn(self.dtype, overlap, valid)


class ArrayIntersect(_ArraySetOp):
    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import binary_validity

        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        keep = _in_row_mask(a) & self._membership(a, b)
        interim = _row_compact(self.dtype, a.data, a.elem_validity,
                               keep, binary_validity(a, b))
        return _distinct_of(interim)


class ArrayExcept(_ArraySetOp):
    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import binary_validity

        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        keep = _in_row_mask(a) & ~self._membership(a, b)
        interim = _row_compact(self.dtype, a.data, a.elem_validity,
                               keep, binary_validity(a, b))
        return _distinct_of(interim)


class ArrayUnion(_ArraySetOp):
    def eval(self, ctx):
        # ConcatArrays already ANDs the input validities; evaluating
        # the children again here would run their subtrees twice
        cat = ConcatArrays(*self.children).eval(ctx)
        return _distinct_of(cat)


def _distinct_of(c: DeviceColumn) -> DeviceColumn:
    """array_distinct over an already-evaluated column."""
    in_row = _in_row_mask(c)
    eq = _elem_eq(c.data[:, :, None], c.data[:, None, :],
                  c.elem_validity[:, :, None],
                  c.elem_validity[:, None, :])
    me = c.data.shape[1]
    earlier = (jnp.arange(me)[None, :, None]
               > jnp.arange(me)[None, None, :])
    both = in_row[:, :, None] & in_row[:, None, :]
    dup = (eq & earlier & both).any(axis=2)
    keep = in_row & ~dup
    return _row_compact(c.dtype, c.data, c.elem_validity, keep,
                        c.validity)
