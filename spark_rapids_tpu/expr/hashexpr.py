"""Murmur3Hash expression (Spark `hash(...)`), bit-exact with CPU Spark.

Backed by the vectorized kernels in ops/hashing.py (the JNI `Hash`
replacement).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.ops.hashing import DEFAULT_SEED, murmur3_columns
from spark_rapids_tpu.sqltypes.datatypes import integer


class Murmur3Hash(Expression):
    def __init__(self, *exprs, seed: int = DEFAULT_SEED):
        super().__init__(list(exprs))
        self.seed = seed

    @property
    def dtype(self):
        return integer

    @property
    def nullable(self):
        return False

    def key(self):
        return ("murmur3", self.seed, tuple(c.key() for c in self.children))

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        h = murmur3_columns(cols, self.seed)
        return DeviceColumn(integer, h, jnp.ones(h.shape, bool))


class XxHash64(Expression):
    """Spark `xxhash64(...)` (seed 42), long result — reference JNI
    Hash.xxhash64."""

    def __init__(self, *exprs, seed: int = 42):
        super().__init__(list(exprs))
        self.seed = seed

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import long

        return long

    @property
    def nullable(self):
        return False

    def key(self):
        return ("xxhash64", self.seed,
                tuple(c.key() for c in self.children))

    def eval(self, ctx):
        from spark_rapids_tpu.ops.hashing import xxhash64_columns

        cols = [c.eval(ctx) for c in self.children]
        h = xxhash64_columns(cols, self.seed)
        return DeviceColumn(self.dtype, h, jnp.ones(h.shape, bool))
