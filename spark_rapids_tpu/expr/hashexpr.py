"""Murmur3Hash expression (Spark `hash(...)`), bit-exact with CPU Spark.

Backed by the vectorized kernels in ops/hashing.py (the JNI `Hash`
replacement).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.ops.hashing import DEFAULT_SEED, murmur3_columns
from spark_rapids_tpu.sqltypes.datatypes import integer


class Murmur3Hash(Expression):
    def __init__(self, *exprs, seed: int = DEFAULT_SEED):
        super().__init__(list(exprs))
        self.seed = seed

    @property
    def dtype(self):
        return integer

    @property
    def nullable(self):
        return False

    def key(self):
        return ("murmur3", self.seed, tuple(c.key() for c in self.children))

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        h = murmur3_columns(cols, self.seed)
        return DeviceColumn(integer, h, jnp.ones(h.shape, bool))
