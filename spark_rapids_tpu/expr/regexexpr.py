"""Regex expressions: RLike (device DFA), RegexpExtract / RegexpReplace
(CPU in v1 — capture groups / replacement need a backtracking engine;
the planner tags their operators for fallback like the reference does
for untranspilable patterns, RegexParser.scala fallback path).
"""

from __future__ import annotations

from typing import Optional

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.regex import (
    CompiledRegex,
    RegexUnsupported,
    compile_search,
)
from spark_rapids_tpu.sqltypes.datatypes import boolean, string


class RLike(Expression):
    """Spark `rlike` / RLIKE: unanchored regex search, device-compiled
    to a DFA when the pattern is in the transpilable subset."""

    def __init__(self, child: Expression, pattern: str):
        super().__init__([child])
        self.pattern = pattern
        self._compiled: Optional[CompiledRegex] = None
        self._compile_error: Optional[str] = None
        try:
            self._compiled = compile_search(pattern)
        except RegexUnsupported as e:
            self._compile_error = str(e)

    @property
    def dtype(self):
        return boolean

    def device_supported(self) -> Optional[str]:
        if self._compiled is None:
            return (f"regex {self.pattern!r} not transpilable to DFA: "
                    f"{self._compile_error}")
        return None

    def key(self):
        return ("rlike", self.pattern, self.children[0].key())

    def eval(self, ctx):
        from spark_rapids_tpu.ops import regexops

        col = self.children[0].eval(ctx)
        m = regexops.dfa_match(col.data, col.lengths, self._compiled)
        return DeviceColumn(boolean, m, col.validity)


class RegexpExtract(Expression):
    """regexp_extract(col, pattern, idx) — CPU in v1 (needs capture
    groups)."""

    def __init__(self, child: Expression, pattern: str, idx: int = 1):
        super().__init__([child])
        self.pattern = pattern
        self.idx = idx

    @property
    def dtype(self):
        return string

    def device_supported(self) -> Optional[str]:
        return "regexp_extract runs on CPU in v1 (capture groups)"

    def key(self):
        return ("regexp_extract", self.pattern, self.idx,
                self.children[0].key())


class RegexpReplace(Expression):
    """regexp_replace(col, pattern, replacement) — CPU in v1."""

    def __init__(self, child: Expression, pattern: str, replacement: str):
        super().__init__([child])
        self.pattern = pattern
        self.replacement = replacement

    @property
    def dtype(self):
        return string

    @property
    def nullable(self):
        return self.children[0].nullable

    def device_supported(self) -> Optional[str]:
        return "regexp_replace runs on CPU in v1"

    def key(self):
        return ("regexp_replace", self.pattern, self.replacement,
                self.children[0].key())
