"""Expression tree core — the GpuExpression analog.

The reference's expressions implement `columnarEval(batch) -> GpuColumnVector`
(`sql-plugin/.../GpuExpressions.scala:155`), each node launching cuDF
kernels. Here `Expression.eval(ctx)` emits jax/jnp ops instead; an entire
projection/filter/aggregation expression tree is traced into ONE XLA
program by the enclosing jitted operator, so per-node fusion is the
compiler's job (the TPU answer to cuDF's AST fused-eval path,
`GpuExpressions.scala:171` convertToAst).

Null semantics follow Spark: every node declares nullability and
propagates validity masks explicitly.

`key()` returns a hashable structural description used to cache compiled
operator programs across batches.
"""

from __future__ import annotations

import math
from typing import Any, List, Optional, Sequence, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import ColumnBatch, DeviceColumn
from spark_rapids_tpu.sqltypes import (
    BooleanType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegerType,
    LongType,
    StringType,
    TimestampType,
)


class EvalContext:
    """Carries the input batch plus derived values during tree evaluation."""

    def __init__(self, batch: ColumnBatch):
        self.batch = batch
        self.live = batch.live_mask()

    @property
    def capacity(self) -> int:
        return self.batch.capacity


class Expression:
    """Base expression node."""

    def __init__(self, children: Sequence["Expression"] = ()):
        self.children = list(children)

    @property
    def dtype(self) -> DataType:
        raise NotImplementedError

    @property
    def nullable(self) -> bool:
        return any(c.nullable for c in self.children)

    def eval(self, ctx: EvalContext) -> DeviceColumn:
        raise NotImplementedError

    def key(self) -> Tuple:
        return (type(self).__name__,
                tuple(c.key() for c in self.children))

    def references(self) -> List[int]:
        out: List[int] = []
        for c in self.children:
            out.extend(c.references())
        return out

    def transform(self, fn) -> "Expression":
        """Bottom-up rewrite; fn(node) returns node or a replacement."""
        new_children = [c.transform(fn) for c in self.children]
        node = self.with_children(new_children)
        return fn(node)

    def with_children(self, children: List["Expression"]) -> "Expression":
        import copy

        node = copy.copy(self)
        node.children = list(children)
        return node

    def __repr__(self):
        cs = ", ".join(repr(c) for c in self.children)
        return f"{type(self).__name__}({cs})"


class BoundReference(Expression):
    """Reference to input column by ordinal (already resolved/bound)."""

    def __init__(self, ordinal: int, dtype: DataType, nullable: bool = True):
        super().__init__()
        self.ordinal = ordinal
        self._dtype = dtype
        self._nullable = nullable

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self._nullable

    def eval(self, ctx: EvalContext) -> DeviceColumn:
        col = ctx.batch.columns[self.ordinal]
        if getattr(col, "encoding", None) is not None:
            # dictionary-encoded columns DECODE here by default, so
            # every downstream expression sees the standard string
            # layout without auditing each one. The consumers that can
            # run on codes (grouping, bare-column projections, the
            # equality/IN/null predicate probes, CodesOf join keys)
            # bypass eval() and read the batch column directly
            # (columnar/encoding.py raw_column / eval_preserving).
            from spark_rapids_tpu.columnar import encoding as _enc

            return _enc.decode_column(col)
        return col

    def key(self):
        return ("ref", self.ordinal, repr(self._dtype))

    def references(self):
        return [self.ordinal]

    def __repr__(self):
        return f"col#{self.ordinal}"


class Literal(Expression):
    def __init__(self, value: Any, dtype: Optional[DataType] = None):
        super().__init__()
        if dtype is None:
            dtype = _infer_literal_type(value)
        self.value = value
        self._dtype = dtype

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return self.value is None

    def eval(self, ctx: EvalContext) -> DeviceColumn:
        cap = ctx.capacity
        dt = self._dtype
        if isinstance(dt, StringType):
            raw = (self.value or "").encode("utf-8")
            mb = max(8, 1 << max(0, (len(raw) - 1)).bit_length())
            mat = np.zeros((1, mb), np.uint8)
            mat[0, :len(raw)] = list(raw)
            data = jnp.broadcast_to(jnp.asarray(mat), (cap, mb))
            lengths = jnp.full((cap,), np.int32(len(raw)))
            valid = jnp.full((cap,), self.value is not None)
            return DeviceColumn(dt, data, valid, lengths)
        from spark_rapids_tpu.ops import decimal128 as _d128

        wide = _d128.is_wide(dt)
        if self.value is None:
            data = jnp.zeros((cap, 2) if wide else (cap,), dt.np_dtype)
            return DeviceColumn(dt, data, jnp.zeros((cap,), bool))
        v = self.value
        if isinstance(dt, DecimalType):
            import decimal

            v = int(decimal.Decimal(str(v)).scaleb(dt.scale)
                    .to_integral_value())
            if wide:
                hi = (v >> 64)
                lo = _d128._i64_bits(v)
                data = jnp.broadcast_to(
                    jnp.asarray([hi, lo], jnp.int64), (cap, 2))
                return DeviceColumn(dt, data, jnp.ones((cap,), bool))
        data = jnp.full((cap,), v, dtype=dt.np_dtype)
        return DeviceColumn(dt, data, jnp.ones((cap,), bool))

    def key(self):
        return ("lit", repr(self.value), repr(self._dtype))

    def __repr__(self):
        return f"lit({self.value!r})"


def _infer_literal_type(v: Any) -> DataType:
    from spark_rapids_tpu.sqltypes.datatypes import (
        boolean, double, integer, long, string,
    )

    if v is None:
        return LongType()
    if isinstance(v, bool):
        return boolean
    if isinstance(v, int):
        return integer if -(2**31) <= v < 2**31 else long
    if isinstance(v, float):
        return double
    if isinstance(v, str):
        return string
    import decimal

    if isinstance(v, decimal.Decimal):
        sign, digits, exp = v.as_tuple()
        scale = max(0, -exp)
        return DecimalType(max(len(digits), scale), scale)
    if isinstance(v, (list, tuple)):
        from spark_rapids_tpu.sqltypes import ArrayType

        elem = next((x for x in v if x is not None), None)
        if elem is None:
            return ArrayType(LongType())
        et = _infer_literal_type(elem)
        if isinstance(elem, int) and not isinstance(elem, bool):
            et = LongType()  # match the common array<bigint> columns
        return ArrayType(et)
    raise TypeError(f"cannot infer literal type for {v!r}")


class Alias(Expression):
    """Named wrapper — transparent at eval time."""

    def __init__(self, child: Expression, name: str):
        super().__init__([child])
        self.name = name

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return self.children[0].nullable

    def eval(self, ctx):
        return self.children[0].eval(ctx)

    def key(self):
        return ("alias", self.children[0].key())

    def __repr__(self):
        return f"{self.children[0]!r} AS {self.name}"


def binary_validity(left: DeviceColumn, right: DeviceColumn) -> jnp.ndarray:
    return left.validity & right.validity
