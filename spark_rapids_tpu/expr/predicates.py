"""Comparison and boolean predicates with Spark null semantics.

Coverage model: the reference's predicate rules in `GpuOverrides.scala`
(EqualTo/LessThan/.../And/Or/Not/IsNull/IsNotNull/IsNaN/InSet, from
:920). And/Or are Kleene three-valued; comparisons propagate null;
EqualNullSafe (`<=>`) never returns null. String comparison is
lexicographic over UTF-8 bytes — identical to Spark's UTF8String binary
ordering — via the packed orderable keys.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import EvalContext, Expression, binary_validity
from spark_rapids_tpu.ops.common import _float_orderable, _string_orderable
from spark_rapids_tpu.sqltypes import (
    BooleanType,
    DoubleType,
    FloatType,
    StringType,
)
from spark_rapids_tpu.sqltypes.datatypes import boolean


def _comparable(col: DeviceColumn) -> List[jnp.ndarray]:
    """Arrays whose tuple-wise lexicographic order == SQL comparison
    order (Spark float comparisons use Java total order for </> with
    NaN greatest)."""
    if isinstance(col.dtype, StringType):
        return _string_orderable(col)
    if isinstance(col.dtype, (FloatType, DoubleType)):
        return [_float_orderable(col.data)]
    if col.data.ndim == 2:  # DECIMAL128 limb matrix
        from spark_rapids_tpu.ops import decimal128 as _d128

        return _d128.orderable_limbs(col.data)
    return [col.data.astype(jnp.int64)]


def _tuple_lt(a: List[jnp.ndarray], b: List[jnp.ndarray]) -> jnp.ndarray:
    lt = jnp.zeros(a[0].shape, bool)
    decided = jnp.zeros(a[0].shape, bool)
    for x, y in zip(a, b):
        lt = jnp.where(~decided & (x < y), True, lt)
        decided = decided | (x != y)
    return lt


def _tuple_eq(a: List[jnp.ndarray], b: List[jnp.ndarray]) -> jnp.ndarray:
    eq = jnp.ones(a[0].shape, bool)
    for x, y in zip(a, b):
        eq = eq & (x == y)
    return eq


class BinaryComparison(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self):
        return boolean

    def _operands(self, ctx: EvalContext):
        lc = self.children[0].eval(ctx)
        rc = self.children[1].eval(ctx)
        # Pad string operands to a common byte width before keying.
        if isinstance(lc.dtype, StringType) and lc.max_bytes != rc.max_bytes:
            mb = max(lc.max_bytes, rc.max_bytes)
            lc = _pad_string(lc, mb)
            rc = _pad_string(rc, mb)
        if lc.dtype != rc.dtype:
            lc, rc = _coerce_numeric(lc, rc)
        return lc, rc


def _coerce_numeric(lc: DeviceColumn, rc: DeviceColumn):
    """Promote mismatched numeric comparison operands to a common type
    (Spark's ImplicitTypeCasts): int-vs-float comparisons must not key
    a raw integer against the float total-order transform, and decimals
    of different scales must align before unscaled-int keying."""
    from spark_rapids_tpu.sqltypes import (
        DecimalType,
        IntegralType,
        NumericType,
    )
    from spark_rapids_tpu.sqltypes.datatypes import double as _double

    lt, rt = lc.dtype, rc.dtype
    if not (isinstance(lt, NumericType) and isinstance(rt, NumericType)):
        return lc, rc
    ld, rd = isinstance(lt, DecimalType), isinstance(rt, DecimalType)
    if ld or rd:
        if isinstance(lt, (FloatType, DoubleType)) or \
                isinstance(rt, (FloatType, DoubleType)):
            # decimal vs float: compare as doubles
            return (_as_double(lc), _as_double(rc))
        ls = lt.scale if ld else 0
        rs = rt.scale if rd else 0
        s = max(ls, rs)
        lp = lt.precision if ld else 19
        rp = rt.precision if rd else 19
        need = max(lp - ls, rp - rs) + s  # digits at the common scale
        if lc.data.ndim == 2 or rc.data.ndim == 2 or need > 18:
            # DECIMAL128 on either side: widen BOTH to limb pairs at
            # the common scale so the limb keys align
            from spark_rapids_tpu.ops import decimal128 as _d128

            out_t = DecimalType(DecimalType.MAX_PRECISION, s)

            def widen(col, delta):
                hi, lo = _d128.widen_column(col, delta)
                return DeviceColumn(out_t, _d128.join(hi, lo),
                                    col.validity)

            return widen(lc, s - ls), widen(rc, s - rs)
        out_t = DecimalType(DecimalType.MAX_LONG_DIGITS, s)
        return (
            DeviceColumn(out_t,
                         lc.data.astype(jnp.int64) * (10 ** (s - ls)),
                         lc.validity, lc.lengths),
            DeviceColumn(out_t,
                         rc.data.astype(jnp.int64) * (10 ** (s - rs)),
                         rc.validity, rc.lengths))
    l_float = isinstance(lt, (FloatType, DoubleType))
    r_float = isinstance(rt, (FloatType, DoubleType))
    if l_float != r_float:
        return _as_double(lc), _as_double(rc)
    if l_float and r_float and lt != rt:
        return _as_double(lc), _as_double(rc)
    # both integral (possibly different widths): int64 keying is exact
    return lc, rc


def _as_double(col: DeviceColumn) -> DeviceColumn:
    from spark_rapids_tpu.sqltypes import DecimalType
    from spark_rapids_tpu.sqltypes.datatypes import double as _double

    if col.data.ndim == 2 and isinstance(col.dtype, DecimalType):
        # DECIMAL128 limb matrix -> approximate double value
        from spark_rapids_tpu.ops import decimal128 as _d128

        data = _d128.to_f64(*_d128.split(col.data)) \
            / (10.0 ** col.dtype.scale)
        return DeviceColumn(_double, data, col.validity)
    data = col.data.astype(jnp.float64)
    if isinstance(col.dtype, DecimalType):
        data = data / (10.0 ** col.dtype.scale)
    return DeviceColumn(_double, data, col.validity)


def _pad_string(col: DeviceColumn, mb: int) -> DeviceColumn:
    if col.max_bytes == mb:
        return col
    return DeviceColumn(
        col.dtype, jnp.pad(col.data, ((0, 0), (0, mb - col.max_bytes))),
        col.validity, col.lengths)


class EqualTo(BinaryComparison):
    def eval(self, ctx):
        from spark_rapids_tpu.columnar import encoding as _enc

        # encoded fast path: `<dictionary column> = <string literal>`
        # compares CODES against one host-probed code — the filter
        # lowering that keeps compressed execution compressed (In and
        # != via Not(EqualTo) compose through this same path)
        fast = _enc.encoded_equality(self.children[0],
                                     self.children[1], ctx)
        if fast is not None:
            return fast
        lc, rc = self._operands(ctx)
        # Spark EqualTo on floats: NaN == NaN is TRUE (total order), and
        # -0.0 == 0.0 is TRUE (IEEE ==). Use IEEE eq for numerics, key eq
        # with NaN canonicalization handled separately.
        if isinstance(lc.dtype, (FloatType, DoubleType)):
            both_nan = jnp.isnan(lc.data) & jnp.isnan(rc.data)
            eq = (lc.data == rc.data) | both_nan
        else:
            eq = _tuple_eq(_comparable(lc), _comparable(rc))
        return DeviceColumn(boolean, eq, binary_validity(lc, rc))


class EqualNullSafe(BinaryComparison):
    """<=>: null <=> null is true; never null."""

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        lc, rc = self._operands(ctx)
        if isinstance(lc.dtype, (FloatType, DoubleType)):
            both_nan = jnp.isnan(lc.data) & jnp.isnan(rc.data)
            veq = (lc.data == rc.data) | both_nan
        else:
            veq = _tuple_eq(_comparable(lc), _comparable(rc))
        both_null = ~lc.validity & ~rc.validity
        both_valid = lc.validity & rc.validity
        res = both_null | (both_valid & veq)
        return DeviceColumn(boolean, res, jnp.ones(res.shape, bool))


class LessThan(BinaryComparison):
    def eval(self, ctx):
        lc, rc = self._operands(ctx)
        if isinstance(lc.dtype, (FloatType, DoubleType)):
            r = lc.data < rc.data
            # Spark: NaN is greater than everything incl. itself for <.
            r = jnp.where(jnp.isnan(lc.data), False, r)
            r = jnp.where(jnp.isnan(rc.data) & ~jnp.isnan(lc.data), True, r)
        else:
            r = _tuple_lt(_comparable(lc), _comparable(rc))
        return DeviceColumn(boolean, r, binary_validity(lc, rc))


class GreaterThan(BinaryComparison):
    def eval(self, ctx):
        return LessThan(self.children[1], self.children[0]).eval(ctx)


class LessThanOrEqual(BinaryComparison):
    def eval(self, ctx):
        gt = LessThan(self.children[1], self.children[0]).eval(ctx)
        return DeviceColumn(boolean, ~gt.data, gt.validity)


class GreaterThanOrEqual(BinaryComparison):
    def eval(self, ctx):
        lt = LessThan(self.children[0], self.children[1]).eval(ctx)
        return DeviceColumn(boolean, ~lt.data, lt.validity)


class And(Expression):
    """Kleene: false & null = false."""

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        lc = self.children[0].eval(ctx)
        rc = self.children[1].eval(ctx)
        lv = lc.validity
        rv = rc.validity
        false_l = lv & ~lc.data
        false_r = rv & ~rc.data
        res = lc.data & rc.data
        valid = (lv & rv) | false_l | false_r
        res = jnp.where(false_l | false_r, False, res)
        return DeviceColumn(boolean, res, valid)


class Or(Expression):
    """Kleene: true | null = true."""

    def __init__(self, left, right):
        super().__init__([left, right])

    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        lc = self.children[0].eval(ctx)
        rc = self.children[1].eval(ctx)
        lv = lc.validity
        rv = rc.validity
        true_l = lv & lc.data
        true_r = rv & rc.data
        res = true_l | true_r
        valid = (lv & rv) | true_l | true_r
        return DeviceColumn(boolean, res, valid)


class Not(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return boolean

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(boolean, ~c.data, c.validity)


class IsNull(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return boolean

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        from spark_rapids_tpu.columnar import encoding as _enc

        # validity needs no decode: read the raw column when the child
        # is a bare reference (keeps encoded columns encoded)
        c = _enc.raw_column(self.children[0], ctx)
        if c is None:
            c = self.children[0].eval(ctx)
        return DeviceColumn(boolean, ~c.validity,
                            jnp.ones(c.validity.shape, bool))


class IsNotNull(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return boolean

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        from spark_rapids_tpu.columnar import encoding as _enc

        c = _enc.raw_column(self.children[0], ctx)
        if c is None:
            c = self.children[0].eval(ctx)
        return DeviceColumn(boolean, c.validity,
                            jnp.ones(c.validity.shape, bool))


class IsNaN(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return boolean

    @property
    def nullable(self):
        return False

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(boolean, jnp.isnan(c.data) & c.validity,
                            jnp.ones(c.validity.shape, bool))


class In(Expression):
    """IN over a literal list (GpuInSet analog)."""

    def __init__(self, child: Expression, values):
        super().__init__([child])
        self.values = list(values)

    @property
    def dtype(self):
        return boolean

    def key(self):
        return ("in", self.children[0].key(), tuple(map(repr, self.values)))

    def eval(self, ctx):
        from spark_rapids_tpu.expr.core import Literal

        c = self.children[0].eval(ctx)
        hit = jnp.zeros(c.data.shape[0], bool)
        any_null = False
        for v in self.values:
            if v is None:
                any_null = True
                continue
            eq = EqualTo(self.children[0], Literal(v, c.dtype)).eval(ctx)
            hit = hit | (eq.data & eq.validity)
        valid = c.validity & (hit | (not any_null))
        return DeviceColumn(boolean, hit, valid)
