"""Declarative aggregate functions with partial/merge/final phases.

Mirrors the reference's aggregate architecture
(`org/apache/spark/sql/rapids/aggregate/aggregateFunctions.scala` +
`GpuAggregateExec.scala:175-400`): each function declares
- update: raw input values -> per-group partial buffers (segmented
  reductions over the sorted/grouped batch),
- merge: partial buffers from many batches/partitions -> combined
  buffers (used after shuffle),
- evaluate: buffers -> final value.

Buffers are plain DeviceColumns, so partial-aggregate results travel
through shuffle like any other batch.
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.ops import segmented
from spark_rapids_tpu.sqltypes import (
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
)
from spark_rapids_tpu.sqltypes.datatypes import double, long


class AggregateFunction(Expression):
    """Base; children[0] is the input expression (if any)."""

    name: str = "agg"

    @property
    def input(self):
        return self.children[0] if self.children else None

    def buffer_types(self) -> List[DataType]:
        raise NotImplementedError

    def update(self, values: DeviceColumn, live, gid, cap
               ) -> List[DeviceColumn]:
        """Segmented partial aggregation over grouped input rows."""
        raise NotImplementedError

    def merge(self, buffers: List[DeviceColumn], live, gid, cap
              ) -> List[DeviceColumn]:
        """Combine partial buffers grouped by key."""
        raise NotImplementedError

    def evaluate(self, buffers: List[DeviceColumn]) -> DeviceColumn:
        raise NotImplementedError


def _sum_result_type(t: DataType) -> DataType:
    if isinstance(t, (FloatType, DoubleType)):
        return double
    if isinstance(t, DecimalType):
        p = min(DecimalType.MAX_LONG_DIGITS, t.precision + 10)
        return DecimalType(p, t.scale)
    return long


class Sum(AggregateFunction):
    name = "sum"

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return _sum_result_type(self.children[0].dtype)

    def buffer_types(self):
        return [self.dtype, long]  # (sum, count_nonnull)

    def update(self, values, live, gid, cap):
        out_t = self.dtype
        valid = values.validity & live
        data = values.data.astype(out_t.np_dtype)
        s = segmented.seg_sum(data, valid, gid, cap)
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(out_t, s, cnt > 0),
                DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]

    def merge(self, buffers, live, gid, cap):
        s = segmented.seg_sum(buffers[0].data,
                              buffers[0].validity & live, gid, cap)
        cnt = segmented.seg_sum(buffers[1].data, live, gid, cap)
        return [DeviceColumn(buffers[0].dtype, s, cnt > 0),
                DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]

    def evaluate(self, buffers):
        return buffers[0]


class Count(AggregateFunction):
    """count(expr) skips nulls; count(*) counts rows (child=None)."""

    name = "count"

    def __init__(self, child: Expression = None):
        super().__init__([child] if child is not None else [])

    @property
    def dtype(self):
        return long

    @property
    def nullable(self):
        return False

    def buffer_types(self):
        return [long]

    def update(self, values, live, gid, cap):
        if values is None:
            valid = live
        else:
            valid = values.validity & live
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]

    def merge(self, buffers, live, gid, cap):
        cnt = segmented.seg_sum(buffers[0].data, live, gid, cap)
        return [DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]

    def evaluate(self, buffers):
        return buffers[0]


class _MinMax(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def buffer_types(self):
        return [self.dtype]

    def _seg(self, data, valid, gid, cap):
        raise NotImplementedError

    def update(self, values, live, gid, cap):
        valid = values.validity & live
        r = self._seg(values.data, valid, gid, cap)
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(self.dtype, r, cnt > 0)]

    def merge(self, buffers, live, gid, cap):
        valid = buffers[0].validity & live
        r = self._seg(buffers[0].data, valid, gid, cap)
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(buffers[0].dtype, r, cnt > 0)]

    def evaluate(self, buffers):
        return buffers[0]


class Min(_MinMax):
    name = "min"

    def _seg(self, data, valid, gid, cap):
        return segmented.seg_min(data, valid, gid, cap)


class Max(_MinMax):
    name = "max"

    def _seg(self, data, valid, gid, cap):
        return segmented.seg_max(data, valid, gid, cap)


class Average(AggregateFunction):
    name = "avg"

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        # Spark: avg(decimal) -> decimal(p+4, s+4); others -> double.
        t = self.children[0].dtype
        if isinstance(t, DecimalType):
            return DecimalType(min(18, t.precision + 4), min(18, t.scale + 4))
        return double

    def buffer_types(self):
        return [_sum_result_type(self.children[0].dtype), long]

    def update(self, values, live, gid, cap):
        return Sum(self.children[0]).update(values, live, gid, cap)

    def merge(self, buffers, live, gid, cap):
        return Sum(self.children[0]).merge(buffers, live, gid, cap)

    def evaluate(self, buffers):
        s, cnt = buffers
        out_t = self.dtype
        safe = jnp.maximum(cnt.data, 1)
        if isinstance(out_t, DecimalType):
            in_t = self.children[0].dtype
            up = out_t.scale - in_t.scale
            num = s.data.astype(jnp.int64) * (10 ** up)
            q = jnp.abs(num) // safe
            rem = jnp.abs(num) - q * safe
            q = q + (2 * rem >= safe).astype(jnp.int64)
            data = jnp.sign(num) * q
        else:
            data = s.data.astype(jnp.float64) / safe.astype(jnp.float64)
        return DeviceColumn(out_t, data, cnt.data > 0)


class First(AggregateFunction):
    name = "first"

    def __init__(self, child: Expression, ignore_nulls: bool = True):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    @property
    def dtype(self):
        return self.children[0].dtype

    def key(self):
        return ("first", self.ignore_nulls, self.children[0].key())

    def buffer_types(self):
        return [self.dtype]

    _take_last = False  # Last flips to a segment_max over positions

    def _first(self, values: DeviceColumn, valid, gid, cap):
        import jax

        n = values.data.shape[0]
        pos = jnp.arange(n, dtype=jnp.int32)
        if self._take_last:
            fp = jax.ops.segment_max(jnp.where(valid, pos, -1), gid,
                                     num_segments=cap)
            found = fp >= 0
        else:
            fp = jax.ops.segment_min(jnp.where(valid, pos, n), gid,
                                     num_segments=cap)
            found = fp < n
        safe = jnp.clip(fp, 0, n - 1)
        data = jnp.take(values.data, safe, axis=0)
        lengths = None if values.lengths is None else jnp.take(
            values.lengths, safe)
        return DeviceColumn(values.dtype, data,
                            found & jnp.take(values.validity, safe), lengths)

    def update(self, values, live, gid, cap):
        valid = live & (values.validity if self.ignore_nulls
                        else jnp.ones_like(live))
        return [self._first(values, valid, gid, cap)]

    def merge(self, buffers, live, gid, cap):
        valid = live & (buffers[0].validity if self.ignore_nulls
                        else jnp.ones_like(live))
        return [self._first(buffers[0], valid, gid, cap)]

    def evaluate(self, buffers):
        return buffers[0]


class Last(First):
    """last(col): final (by sorted position) value per group — First
    with segment_max over positions."""

    name = "last"
    _take_last = True

    def key(self):
        return ("last", self.ignore_nulls, self.children[0].key())
