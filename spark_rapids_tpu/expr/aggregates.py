"""Declarative aggregate functions with partial/merge/final phases.

Mirrors the reference's aggregate architecture
(`org/apache/spark/sql/rapids/aggregate/aggregateFunctions.scala` +
`GpuAggregateExec.scala:175-400`): each function declares
- update: raw input values -> per-group partial buffers (segmented
  reductions over the sorted/grouped batch),
- merge: partial buffers from many batches/partitions -> combined
  buffers (used after shuffle),
- evaluate: buffers -> final value.

Buffers are plain DeviceColumns, so partial-aggregate results travel
through shuffle like any other batch.
"""

from __future__ import annotations

from typing import List

import jax
import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.ops import segmented
from spark_rapids_tpu.sqltypes import (
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
)
from spark_rapids_tpu.sqltypes.datatypes import boolean, double, long


class AggregateFunction(Expression):
    """Base; children are the input expressions (if any).

    `jittable=False` marks functions whose update/merge need dynamic
    output shapes (collect_list and friends); the aggregate exec runs
    those phases eagerly instead of under jax.jit.
    """

    name: str = "agg"
    jittable: bool = True
    #: False for functions whose update/merge require CONTIGUOUS sorted
    #: segments (the collect family's rank computation); the aggregate
    #: exec then keeps the sorted grouping even when keys are binnable.
    binned_safe: bool = True

    @property
    def input(self):
        return self.children[0] if self.children else None

    def buffer_types(self) -> List[DataType]:
        raise NotImplementedError

    def update(self, values: DeviceColumn, live, gid, cap
               ) -> List[DeviceColumn]:
        """Segmented partial aggregation over grouped input rows."""
        raise NotImplementedError

    def merge(self, buffers: List[DeviceColumn], live, gid, cap
              ) -> List[DeviceColumn]:
        """Combine partial buffers grouped by key."""
        raise NotImplementedError

    def evaluate(self, buffers: List[DeviceColumn]) -> DeviceColumn:
        raise NotImplementedError


def _sum_result_type(t: DataType) -> DataType:
    if isinstance(t, (FloatType, DoubleType)):
        return double
    if isinstance(t, DecimalType):
        # Spark: sum(decimal(p,s)) -> decimal(p+10, s); beyond 18 digits
        # the buffer/result is DECIMAL128 (limb pairs, ops/decimal128.py)
        p = min(DecimalType.MAX_PRECISION, t.precision + 10)
        return DecimalType(p, t.scale)
    return long


class Sum(AggregateFunction):
    name = "sum"

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return _sum_result_type(self.children[0].dtype)

    def buffer_types(self):
        return [self.dtype, long]  # (sum, count_nonnull)

    def update(self, values, live, gid, cap):
        from spark_rapids_tpu.ops import decimal128 as d128

        out_t = self.dtype
        valid = values.validity & live
        if d128.is_wide(out_t):
            cnt = segmented.seg_count(valid, gid, cap)
            hi, lo = d128.widen_column(values)
            sh, sl = d128.seg_sum128(hi, lo, valid, gid, cap)
            return [DeviceColumn(out_t, d128.join(sh, sl), cnt > 0),
                    DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]
        vb = segmented.infer_int_vbound(values)
        data = values.data.astype(out_t.np_dtype)
        s, cnt = segmented.seg_sum_count(data, valid, gid, cap, vbound=vb)
        return [DeviceColumn(out_t, s, cnt > 0),
                DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]

    def merge(self, buffers, live, gid, cap):
        from spark_rapids_tpu.ops import decimal128 as d128

        cnt = segmented.seg_sum(buffers[1].data, live, gid, cap)
        ones = jnp.ones(cnt.shape, bool)
        buf = buffers[0]
        if buf.data.ndim == 2:
            hi, lo = d128.split(buf.data)
            sh, sl = d128.seg_sum128(hi, lo, buf.validity & live, gid,
                                     cap)
            return [DeviceColumn(buf.dtype, d128.join(sh, sl), cnt > 0),
                    DeviceColumn(long, cnt, ones)]
        s = segmented.seg_sum(buf.data, buf.validity & live, gid, cap)
        return [DeviceColumn(buf.dtype, s, cnt > 0),
                DeviceColumn(long, cnt, ones)]

    def evaluate(self, buffers):
        return buffers[0]


class Count(AggregateFunction):
    """count(expr) skips nulls; count(*) counts rows (child=None)."""

    name = "count"

    def __init__(self, child: Expression = None):
        super().__init__([child] if child is not None else [])

    @property
    def dtype(self):
        return long

    @property
    def nullable(self):
        return False

    def buffer_types(self):
        return [long]

    def update(self, values, live, gid, cap):
        if values is None:
            valid = live
        else:
            valid = values.validity & live
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]

    def merge(self, buffers, live, gid, cap):
        cnt = segmented.seg_sum(buffers[0].data, live, gid, cap)
        return [DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))]

    def evaluate(self, buffers):
        return buffers[0]


class _MinMax(AggregateFunction):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def buffer_types(self):
        return [self.dtype]

    def _seg(self, data, valid, gid, cap):
        raise NotImplementedError

    def _seg_any(self, data, valid, gid, cap):
        if data.ndim != 2:
            return self._seg(data, valid, gid, cap)
        # DECIMAL128: two-pass segmented extremum over (hi, lo') limbs
        from spark_rapids_tpu.ops import decimal128 as d128

        hi, lo = d128.split(data)
        lo_o = lo ^ jnp.int64(d128._SIGN64)  # unsigned-orderable
        h = self._seg(hi, valid, gid, cap)
        tie = valid & (hi == jnp.take(h, gid))
        l_o = self._seg(lo_o, tie, gid, cap)
        return d128.join(h, l_o ^ jnp.int64(d128._SIGN64))

    def update(self, values, live, gid, cap):
        valid = values.validity & live
        r = self._seg_any(values.data, valid, gid, cap)
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(self.dtype, r, cnt > 0)]

    def merge(self, buffers, live, gid, cap):
        valid = buffers[0].validity & live
        r = self._seg_any(buffers[0].data, valid, gid, cap)
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(buffers[0].dtype, r, cnt > 0)]

    def evaluate(self, buffers):
        return buffers[0]


class Min(_MinMax):
    name = "min"

    def _seg(self, data, valid, gid, cap):
        return segmented.seg_min(data, valid, gid, cap)


class Max(_MinMax):
    name = "max"

    def _seg(self, data, valid, gid, cap):
        return segmented.seg_max(data, valid, gid, cap)


class Average(AggregateFunction):
    name = "avg"

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        # Spark: avg(decimal) -> decimal(p+4, s+4); others -> double.
        t = self.children[0].dtype
        if isinstance(t, DecimalType):
            m = DecimalType.MAX_PRECISION
            return DecimalType(min(m, t.precision + 4),
                               min(m, t.scale + 4))
        return double

    def buffer_types(self):
        return [_sum_result_type(self.children[0].dtype), long]

    def update(self, values, live, gid, cap):
        return Sum(self.children[0]).update(values, live, gid, cap)

    def merge(self, buffers, live, gid, cap):
        return Sum(self.children[0]).merge(buffers, live, gid, cap)

    def evaluate(self, buffers):
        from spark_rapids_tpu.ops import decimal128 as d128

        s, cnt = buffers
        out_t = self.dtype
        safe = jnp.maximum(cnt.data, 1)
        if isinstance(out_t, DecimalType) and s.data.ndim == 2:
            in_t = self.children[0].dtype
            up = out_t.scale - in_t.scale
            hi, lo = d128.rescale(*d128.split(s.data), up)
            qh, ql = d128.div128_round_half_up(hi, lo, safe)
            valid = (cnt.data > 0) & d128.fits_precision(
                qh, ql, out_t.precision)
            if d128.is_wide(out_t):
                return DeviceColumn(out_t, d128.join(qh, ql), valid)
            return DeviceColumn(out_t, ql,
                                valid & d128.fits_i64(qh, ql))
        if isinstance(out_t, DecimalType):
            in_t = self.children[0].dtype
            up = out_t.scale - in_t.scale
            num = s.data.astype(jnp.int64) * (10 ** up)
            q = jnp.abs(num) // safe
            rem = jnp.abs(num) - q * safe
            q = q + (2 * rem >= safe).astype(jnp.int64)
            data = jnp.sign(num) * q
        else:
            data = s.data.astype(jnp.float64) / safe.astype(jnp.float64)
        return DeviceColumn(out_t, data, cnt.data > 0)


class First(AggregateFunction):
    name = "first"

    def __init__(self, child: Expression, ignore_nulls: bool = True):
        super().__init__([child])
        self.ignore_nulls = ignore_nulls

    @property
    def dtype(self):
        return self.children[0].dtype

    def key(self):
        return ("first", self.ignore_nulls, self.children[0].key())

    def buffer_types(self):
        return [self.dtype]

    _take_last = False  # Last flips to a segment_max over positions

    def _first(self, values: DeviceColumn, valid, gid, cap):
        n = values.data.shape[0]
        pos = jnp.arange(n, dtype=jnp.int32)
        ones = jnp.ones((n,), bool)
        if self._take_last:
            fp = segmented.seg_max(jnp.where(valid, pos, -1), ones,
                                   gid, cap)
            found = fp >= 0
        else:
            fp = segmented.seg_min(jnp.where(valid, pos, n), ones,
                                   gid, cap)
            found = fp < n
        safe = jnp.clip(fp, 0, n - 1)
        data = jnp.take(values.data, safe, axis=0)
        lengths = None if values.lengths is None else jnp.take(
            values.lengths, safe)
        return DeviceColumn(values.dtype, data,
                            found & jnp.take(values.validity, safe), lengths)

    def update(self, values, live, gid, cap):
        valid = live & (values.validity if self.ignore_nulls
                        else jnp.ones_like(live))
        return [self._first(values, valid, gid, cap)]

    def merge(self, buffers, live, gid, cap):
        valid = live & (buffers[0].validity if self.ignore_nulls
                        else jnp.ones_like(live))
        return [self._first(buffers[0], valid, gid, cap)]

    def evaluate(self, buffers):
        return buffers[0]


class Last(First):
    """last(col): final (by sorted position) value per group — First
    with segment_max over positions."""

    name = "last"
    _take_last = True

    def key(self):
        return ("last", self.ignore_nulls, self.children[0].key())


class AnyValue(First):
    """any_value(col): any value from the group (reference registers it
    as a First-family aggregate)."""

    name = "any_value"

    def key(self):
        return ("any_value", self.ignore_nulls, self.children[0].key())


class GroupingID(Expression):
    """Marker for F.grouping_id(); rewritten by rollup/cube/grouping-
    sets agg() into a reference to the synthesized grouping-id column.
    Invalid outside those contexts (as in Spark)."""

    @property
    def dtype(self):
        return long

    @property
    def nullable(self):
        return False


class GroupingBit(Expression):
    """Marker for F.grouping(col): 1 when the column is aggregated
    (masked) in the grouping set, else 0."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return long

    @property
    def nullable(self):
        return False


# --------------------------------------------------------- moment family
#
# Variance/stddev/skewness/kurtosis over raw power sums (n, Σx, Σx²,…)
# — the declarative-buffer design of the reference's M2-based aggregates
# (aggregateFunctions.scala GpuStddevPop/GpuVarianceSamp etc.) with
# power sums instead of streaming M2 so partial/merge are plain
# segmented additions (one XLA segment_sum per buffer).


class _Moments(AggregateFunction):
    """Buffers: [n (long), Σx, Σx², … Σx^k (double)]."""

    n_powers = 2

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return double

    def buffer_types(self):
        return [long] + [double] * self.n_powers

    def update(self, values, live, gid, cap):
        valid = values.validity & live
        x = values.data.astype(jnp.float64)
        powers = [x]
        for _ in range(self.n_powers - 1):
            powers.append(powers[-1] * x)
        cnt, sums = segmented.seg_multi_sum(powers, valid, gid, cap)
        ones = jnp.ones(cnt.shape, bool)
        return ([DeviceColumn(long, cnt, ones)]
                + [DeviceColumn(double, s, cnt > 0) for s in sums])

    def merge(self, buffers, live, gid, cap):
        cnt = segmented.seg_sum(buffers[0].data, live, gid, cap)
        ones = jnp.ones(cnt.shape, bool)
        out = [DeviceColumn(long, cnt, ones)]
        for b in buffers[1:]:
            s = segmented.seg_sum(b.data, b.validity & live, gid, cap)
            out.append(DeviceColumn(double, s, cnt > 0))
        return out

    @staticmethod
    def _m2(n, s1, s2):
        """Central second moment Σ(x-μ)² = Σx² - (Σx)²/n."""
        safe = jnp.maximum(n, 1.0)
        return s2 - s1 * s1 / safe

    def evaluate(self, buffers):
        raise NotImplementedError


class VariancePop(_Moments):
    name = "var_pop"

    def evaluate(self, buffers):
        n = buffers[0].data.astype(jnp.float64)
        m2 = self._m2(n, buffers[1].data, buffers[2].data)
        data = jnp.maximum(m2, 0.0) / jnp.maximum(n, 1.0)
        return DeviceColumn(double, data, n >= 1)


class VarianceSamp(_Moments):
    """var_samp: NULL for n<2 (Spark 3.x default,
    spark.sql.legacy.statisticalAggregate=false)."""

    name = "var_samp"

    def evaluate(self, buffers):
        n = buffers[0].data.astype(jnp.float64)
        m2 = self._m2(n, buffers[1].data, buffers[2].data)
        data = jnp.maximum(m2, 0.0) / jnp.maximum(n - 1.0, 1.0)
        return DeviceColumn(double, data, n >= 2)


class StddevPop(VariancePop):
    name = "stddev_pop"

    def evaluate(self, buffers):
        v = super().evaluate(buffers)
        return DeviceColumn(double, jnp.sqrt(v.data), v.validity)


class StddevSamp(VarianceSamp):
    name = "stddev_samp"

    def evaluate(self, buffers):
        v = super().evaluate(buffers)
        return DeviceColumn(double, jnp.sqrt(v.data), v.validity)


class Skewness(_Moments):
    """skewness = sqrt(n)·m3 / m2^1.5 (NULL when n=0 or m2=0)."""

    name = "skewness"
    n_powers = 3

    def evaluate(self, buffers):
        n = buffers[0].data.astype(jnp.float64)
        s1, s2, s3 = (b.data for b in buffers[1:])
        safe = jnp.maximum(n, 1.0)
        mu = s1 / safe
        m2 = jnp.maximum(s2 - s1 * mu, 0.0)
        m3 = s3 - 3.0 * mu * s2 + 2.0 * mu * mu * s1
        den = jnp.maximum(m2, 1e-300) ** 1.5
        data = jnp.sqrt(safe) * m3 / den
        return DeviceColumn(double, data, (n >= 1) & (m2 > 0))


class Kurtosis(_Moments):
    """kurtosis (excess) = n·m4/m2² - 3 (NULL when n=0 or m2=0)."""

    name = "kurtosis"
    n_powers = 4

    def evaluate(self, buffers):
        n = buffers[0].data.astype(jnp.float64)
        s1, s2, s3, s4 = (b.data for b in buffers[1:])
        safe = jnp.maximum(n, 1.0)
        mu = s1 / safe
        m2 = jnp.maximum(s2 - s1 * mu, 0.0)
        m4 = (s4 - 4.0 * mu * s3 + 6.0 * mu * mu * s2
              - 3.0 * mu ** 3 * s1)
        den = jnp.maximum(m2 * m2, 1e-300)
        data = safe * m4 / den - 3.0
        return DeviceColumn(double, data, (n >= 1) & (m2 > 0))


# ------------------------------------------------------ bivariate family


class _Bivariate(AggregateFunction):
    """Two-input aggregates (corr / covar_*). A row participates only
    when BOTH inputs are non-null (Spark semantics). Buffers:
    [n, Σx, Σy, Σxy] (+ Σx², Σy² for corr)."""

    extra_squares = False

    def __init__(self, x: Expression, y: Expression):
        super().__init__([x, y])

    @property
    def dtype(self):
        return double

    def buffer_types(self):
        return [long] + [double] * (5 if self.extra_squares else 3)

    def update(self, values, live, gid, cap):
        xc, yc = values
        valid = xc.validity & yc.validity & live
        x = xc.data.astype(jnp.float64)
        y = yc.data.astype(jnp.float64)
        vecs = [x, y, x * y]
        if self.extra_squares:
            vecs += [x * x, y * y]
        cnt, sums = segmented.seg_multi_sum(vecs, valid, gid, cap)
        ones = jnp.ones(cnt.shape, bool)
        return ([DeviceColumn(long, cnt, ones)]
                + [DeviceColumn(double, s, cnt > 0) for s in sums])

    def merge(self, buffers, live, gid, cap):
        cnt = segmented.seg_sum(buffers[0].data, live, gid, cap)
        ones = jnp.ones(cnt.shape, bool)
        out = [DeviceColumn(long, cnt, ones)]
        for b in buffers[1:]:
            out.append(DeviceColumn(
                double, segmented.seg_sum(b.data, b.validity & live, gid,
                                          cap), cnt > 0))
        return out


class CovarPop(_Bivariate):
    name = "covar_pop"

    def evaluate(self, buffers):
        n = buffers[0].data.astype(jnp.float64)
        sx, sy, sxy = (b.data for b in buffers[1:4])
        safe = jnp.maximum(n, 1.0)
        data = (sxy - sx * sy / safe) / safe
        return DeviceColumn(double, data, n >= 1)


class CovarSamp(_Bivariate):
    name = "covar_samp"

    def evaluate(self, buffers):
        n = buffers[0].data.astype(jnp.float64)
        sx, sy, sxy = (b.data for b in buffers[1:4])
        safe = jnp.maximum(n, 1.0)
        data = (sxy - sx * sy / safe) / jnp.maximum(n - 1.0, 1.0)
        return DeviceColumn(double, data, n >= 2)


class Corr(_Bivariate):
    """Pearson correlation; NULL when n=0 or either variance is 0."""

    name = "corr"
    extra_squares = True

    def evaluate(self, buffers):
        n = buffers[0].data.astype(jnp.float64)
        sx, sy, sxy, sxx, syy = (b.data for b in buffers[1:6])
        safe = jnp.maximum(n, 1.0)
        cov = sxy - sx * sy / safe
        vx = jnp.maximum(sxx - sx * sx / safe, 0.0)
        vy = jnp.maximum(syy - sy * sy / safe, 0.0)
        den = jnp.sqrt(vx) * jnp.sqrt(vy)
        data = cov / jnp.maximum(den, 1e-300)
        return DeviceColumn(double, jnp.clip(data, -1.0, 1.0),
                            (n >= 1) & (den > 0))


# ----------------------------------------------------------- bool family


class _BoolReduce(AggregateFunction):
    _use_max = False  # bool_or reduces with max, bool_and with min

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return boolean

    def buffer_types(self):
        return [boolean]

    def _seg(self, data, valid, gid, cap):
        x = data.astype(jnp.int32)
        if self._use_max:
            r = segmented.seg_max(x, valid, gid, cap)
        else:
            r = segmented.seg_min(x, valid, gid, cap)
        return r > 0

    def update(self, values, live, gid, cap):
        valid = values.validity & live
        r = self._seg(values.data, valid, gid, cap)
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(boolean, r, cnt > 0)]

    def merge(self, buffers, live, gid, cap):
        valid = buffers[0].validity & live
        r = self._seg(buffers[0].data, valid, gid, cap)
        cnt = segmented.seg_count(valid, gid, cap)
        return [DeviceColumn(boolean, r, cnt > 0)]

    def evaluate(self, buffers):
        return buffers[0]


class BoolAnd(_BoolReduce):
    name = "bool_and"


class BoolOr(_BoolReduce):
    name = "bool_or"
    _use_max = True


# ------------------------------------------------- collect / exact sets
#
# collect_list/collect_set produce ArrayType results; their buffers are
# array columns ([cap, max_elems] padded matrices). max_elems is data-
# dependent (the largest group), so update/merge run EAGERLY
# (jittable=False) — jax eager mode allows the dynamic output width
# while keeping the compute on device. Reference: cuDF collect_list /
# collect_set GroupByAggregations (GpuAggregateExec + cuDF ragged
# lists); here the ragged result is the padded-matrix array layout of
# columnar/batch.py.


def _eq_nan_aware(a, b):
    """Element equality where NaN == NaN (Spark set semantics: collect_set
    and count(DISTINCT) treat NaN as equal to itself)."""
    eq = a == b
    if jnp.issubdtype(a.dtype, jnp.floating):
        eq = eq | (jnp.isnan(a) & jnp.isnan(b))
    return eq


def _seg_exclusive_ranks(valid, gid, cap):
    """Rank of each valid row within its (contiguous, sorted) segment."""
    csum = jnp.cumsum(valid.astype(jnp.int32)) - valid.astype(jnp.int32)
    n = valid.shape[0]
    # contiguous gid: first position of segment g by binary search
    fp = jnp.searchsorted(gid, jnp.arange(cap, dtype=gid.dtype),
                          side="left")
    base = jnp.take(csum, jnp.clip(fp, 0, n - 1))
    return csum - jnp.take(base, gid)


class CollectList(AggregateFunction):
    name = "collect_list"
    jittable = False
    binned_safe = False  # _seg_exclusive_ranks needs sorted gids

    #: Traced-mode (mesh SPMD) sizing: when set, the element matrix is
    #: this static width instead of the eager largest-group host sync;
    #: groups wider than the width set `_overflow` (a traced bool the
    #: mesh executor folds into its expansion-retry flag, the same
    #: static-capacity + recompile-bigger discipline as the
    #: collectives). None = eager data-dependent sizing.
    _static_width = None
    _overflow = None

    def begin_static(self, width: int) -> None:
        self._static_width = int(width)
        self._overflow = jnp.zeros((), bool)

    def end_static(self):
        ovf = self._overflow
        self._static_width = None
        self._overflow = None
        return ovf

    def key(self):
        return (self.name, self._static_width,
                self.children[0].key())

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes import ArrayType

        return ArrayType(self.children[0].dtype, containsNull=False)

    @property
    def nullable(self):
        return False  # empty array, never null (Spark collect_list)

    def buffer_types(self):
        return [self.dtype]

    def _scatter(self, elem_dt, vals, valid, gid, cap):
        """Rows -> [cap, me] padded array column (me = largest group,
        or the static traced-mode width)."""
        cnt = segmented.seg_count(valid, gid, cap)
        if self._static_width is not None:
            me = self._static_width
            self._overflow = self._overflow | jnp.any(cnt > me)
            cnt = jnp.minimum(cnt, me)  # ranks >= me scatter out of
            #                             bounds and drop (mode="drop")
        else:
            me = max(int(jnp.max(cnt)), 1)
        rank = _seg_exclusive_ranks(valid, gid, cap)
        # invalid rows scatter out of range and are dropped
        col = jnp.where(valid, rank, me)
        out = jnp.zeros((cap, me), vals.dtype)
        out = out.at[gid, col].set(vals, mode="drop")
        ev = (jnp.arange(me, dtype=jnp.int32)[None, :] < cnt[:, None])
        from spark_rapids_tpu.sqltypes import ArrayType

        # collect_* is never NULL (empty array for all-null groups);
        # rows past num_groups are sliced away by the batch row count.
        return DeviceColumn(ArrayType(elem_dt, False), out,
                            jnp.ones(cap, bool), cnt.astype(jnp.int32), ev)

    def update(self, values, live, gid, cap):
        valid = values.validity & live
        return [self._scatter(values.dtype, values.data, valid, gid, cap)]

    def _merge_elements(self, buf, live, gid, cap, dedup: bool):
        """Flatten each group's row-lists into per-element rows, then
        re-scatter per group (optionally deduplicating)."""
        me_in = buf.data.shape[1] if buf.data.ndim == 2 else 1
        n = buf.data.shape[0]
        vals = buf.data.reshape(n * me_in)
        egid = jnp.repeat(gid, me_in)
        within = jnp.arange(me_in, dtype=jnp.int32)[None, :]
        evalid = ((within < buf.lengths[:, None])
                  & live[:, None]).reshape(n * me_in)
        if buf.elem_validity is not None:
            evalid = evalid & buf.elem_validity.reshape(n * me_in)
        if dedup:
            # sort invalid (padding) elements to each segment's end so
            # equal valid values are adjacent for the dup test
            order = jnp.lexsort((vals, ~evalid, egid))
            vals = jnp.take(vals, order)
            egid = jnp.take(egid, order)
            evalid = jnp.take(evalid, order)
            prev_same = jnp.concatenate([
                jnp.array([False]),
                (egid[1:] == egid[:-1])
                & _eq_nan_aware(vals[1:], vals[:-1]) & evalid[:-1]])
            evalid = evalid & ~prev_same
        elem_dt = buf.dtype.elementType
        return self._scatter(elem_dt, vals, evalid, egid, cap)

    def merge(self, buffers, live, gid, cap):
        return [self._merge_elements(buffers[0], live, gid, cap,
                                     dedup=False)]

    def evaluate(self, buffers):
        return buffers[0]


class CollectSet(CollectList):
    """collect_set: distinct values per group. update deduplicates
    within the batch segment; merge deduplicates across partials."""

    name = "collect_set"

    def update(self, values, live, gid, cap):
        valid = values.validity & live
        vals = values.data
        order = jnp.lexsort((vals, ~valid, gid))
        svals = jnp.take(vals, order)
        sgid = jnp.take(gid, order)
        svalid = jnp.take(valid, order)
        prev_same = jnp.concatenate([
            jnp.array([False]),
            (sgid[1:] == sgid[:-1])
            & _eq_nan_aware(svals[1:], svals[:-1]) & svalid[:-1]])
        keep = svalid & ~prev_same
        return [self._scatter(values.dtype, svals, keep, sgid, cap)]

    def merge(self, buffers, live, gid, cap):
        return [self._merge_elements(buffers[0], live, gid, cap,
                                     dedup=True)]


class CountDistinct(AggregateFunction):
    """count(DISTINCT col) — CollectSet buffers, cardinality at
    evaluate (the planner's Expand-based distinct rewrite in Spark,
    collapsed into one set-buffer aggregate here)."""

    name = "count_distinct"
    jittable = False
    binned_safe = False  # delegates to the collect-set buffer

    def __init__(self, child: Expression):
        super().__init__([child])

    # traced-mode static sizing delegates to the underlying set buffer
    _static_width = None
    _overflow = None
    begin_static = CollectList.begin_static
    end_static = CollectList.end_static

    def key(self):
        return (self.name, self._static_width,
                self.children[0].key())

    @property
    def _set(self):
        # derived lazily: children are rebound during plan analysis;
        # the throwaway delegate carries this instance's traced-mode
        # state in and out
        s = CollectSet(self.children[0])
        s._static_width = self._static_width
        s._overflow = self._overflow
        return s

    def _delegated(self, s: "CollectSet", out):
        if s._static_width is not None:
            self._overflow = s._overflow
        return out

    @property
    def dtype(self):
        return long

    @property
    def nullable(self):
        return False

    def buffer_types(self):
        return self._set.buffer_types()

    def update(self, values, live, gid, cap):
        s = self._set
        return self._delegated(s, s.update(values, live, gid, cap))

    def merge(self, buffers, live, gid, cap):
        s = self._set
        return self._delegated(s, s.merge(buffers, live, gid, cap))

    def evaluate(self, buffers):
        buf = buffers[0]
        cnt = buf.lengths.astype(jnp.int64)
        return DeviceColumn(long, cnt, jnp.ones(cnt.shape, bool))


class SumDistinct(CountDistinct):
    name = "sum_distinct"

    @property
    def dtype(self):
        return _sum_result_type(self.children[0].dtype)

    @property
    def nullable(self):
        return True

    def evaluate(self, buffers):
        buf = buffers[0]
        me = buf.data.shape[1]
        mask = (jnp.arange(me, dtype=jnp.int32)[None, :]
                < buf.lengths[:, None])
        out_t = self.dtype
        data = jnp.where(mask, buf.data.astype(out_t.np_dtype), 0).sum(
            axis=1)
        return DeviceColumn(out_t, data, buf.lengths > 0)


class Percentile(AggregateFunction):
    """Exact percentile with linear interpolation (Spark `percentile`).
    Buffers collect the group's raw values (the reference's exact
    GpuPercentile accumulates a value->count histogram via JNI
    Histogram; the padded-array buffer plays that role here), so this
    is for group sizes that fit a device row — the same practical
    envelope as the reference's exact path."""

    name = "percentile"
    jittable = False
    binned_safe = False  # collect-list buffers (sorted-gid ranks)

    def __init__(self, child: Expression, percentage: float,
                 accuracy: int = 10000):
        super().__init__([child])
        self.percentage = float(percentage)
        self.accuracy = int(accuracy)

    @property
    def _list(self):
        # derived lazily: children are rebound during plan analysis
        return CollectList(self.children[0])

    @property
    def dtype(self):
        return double

    def key(self):
        return (self.name, self.percentage, self.children[0].key())

    def buffer_types(self):
        return self._list.buffer_types()

    def update(self, values, live, gid, cap):
        return self._list.update(values, live, gid, cap)

    def merge(self, buffers, live, gid, cap):
        return self._list.merge(buffers, live, gid, cap)

    def evaluate(self, buffers):
        buf = buffers[0]
        me = buf.data.shape[1]
        cnt = buf.lengths
        mask = (jnp.arange(me, dtype=jnp.int32)[None, :] < cnt[:, None])
        vals = jnp.where(mask, buf.data.astype(jnp.float64), jnp.inf)
        svals = jnp.sort(vals, axis=1)
        rk = self.percentage * jnp.maximum(cnt - 1, 0).astype(jnp.float64)
        lo = jnp.floor(rk).astype(jnp.int32)
        hi = jnp.ceil(rk).astype(jnp.int32)
        frac = rk - lo
        safe_lo = jnp.clip(lo, 0, me - 1)
        safe_hi = jnp.clip(hi, 0, me - 1)
        vlo = jnp.take_along_axis(svals, safe_lo[:, None], axis=1)[:, 0]
        vhi = jnp.take_along_axis(svals, safe_hi[:, None], axis=1)[:, 0]
        data = vlo + (vhi - vlo) * frac
        return DeviceColumn(double, data, cnt > 0)


class ApproxPercentile(Percentile):
    """approx_percentile as a BOUNDED, MERGEABLE quantile sketch — the
    t-digest role (reference GpuApproximatePercentile.scala + JNI
    t-digest), re-designed for XLA's static shapes.

    binned_safe again (unlike the exact path): update/merge sort by
    gid themselves, so unsorted binned gids are fine.

    The sketch is K equally-spaced quantile points + a count per group
    (K derives from `accuracy`, capped so the buffer stays K+1 device
    columns regardless of group size — unlike the exact path's
    padded-array buffer, memory is O(K) per group):
    - update: sort rows by (group, value), gather each group's
      rank-floor(q_j * (n-1)) values — one device sort + K gathers;
    - merge: treat every partial's points as weight-(n/K) samples,
      sort the flattened points by (group, value), and re-extract the
      K combined quantiles by segmented weighted-rank selection;
    - evaluate: interpolate `percentage` over the K points.

    Rank error is O(1/K) per merge level (vs the reference t-digest's
    O(1/accuracy)); both satisfy "approximate" with bounded buffers,
    which is what matters at scale — and jittable=True means this
    lowers into the mesh SPMD program and the fused single-chip
    engine, which the exact collect-based path cannot.
    """

    name = "approx_percentile"
    jittable = True
    binned_safe = True

    def key(self):
        # K shapes the buffer schema and the jitted partial/merge
        # programs — cache entries must not collide across accuracies
        return (self.name, self.percentage, self.K,
                self.children[0].key())

    @property
    def K(self) -> int:
        return int(min(max(self.accuracy, 16), 128))

    def buffer_types(self):
        return [double] * self.K + [long]

    def _extract(self, svals, sw_gid, live_s, pos, cap, weights=None):
        """Shared rank-selection over (group, value)-sorted points.
        Returns K [cap] arrays indexed by group id + count/weight.

        `cap` is the number of segments (groups); the POSITION domain is
        len(pos), which differs in merge (cap*K flattened points) — the
        sentinel and clip bounds must use it, not cap."""
        npos = int(pos.shape[0])
        if weights is None:
            weights = jnp.where(live_s, 1.0, 0.0)
        total = jax.ops.segment_sum(weights, sw_gid, num_segments=cap)
        first = jax.ops.segment_min(
            jnp.where(weights > 0, pos, jnp.int32(npos)), sw_gid,
            num_segments=cap)
        # exclusive running weight within the group
        cw = jnp.cumsum(weights)
        base = jnp.take(cw - weights, jnp.clip(first, 0, npos - 1))
        cw_in = (cw - weights) - jnp.take(base, sw_gid)
        outs = []
        K = self.K
        for j in range(K):
            q = j / max(K - 1, 1)
            tgt = q * jnp.take(total, sw_gid)
            hit = (weights > 0) & (cw_in + weights >= tgt - 1e-12)
            p = jax.ops.segment_min(
                jnp.where(hit, pos, jnp.int32(npos)), sw_gid,
                num_segments=cap)
            outs.append(jnp.take(svals, jnp.clip(p, 0, npos - 1)))
        return outs, total

    def update(self, values, live, gid, cap):
        valid = live & values.validity
        v = values.data.astype(jnp.float64)
        from spark_rapids_tpu.ops.common import sort_permutation

        # row domain (gid length) and segment domain (cap) differ under
        # the binned grouping, which keeps groups at bin-count capacity
        nrow = int(gid.shape[0])
        rank = jnp.where(valid, 0, 1).astype(jnp.int32)
        key_v = jnp.where(valid, v, jnp.inf)
        perm = sort_permutation(
            [gid.astype(jnp.int64), rank.astype(jnp.int64), key_v], nrow)
        svals = jnp.take(key_v, perm)
        sgid = jnp.take(gid, perm)
        slive = jnp.take(valid, perm)
        pos = jnp.arange(nrow, dtype=jnp.int32)
        outs, total = self._extract(svals, sgid, slive, pos, cap)
        n = total.astype(jnp.int64)
        ok = n > 0
        cols = [DeviceColumn(double, o, ok) for o in outs]
        cols.append(DeviceColumn(long, n, jnp.ones((cap,), bool)))
        return cols

    def merge(self, buffers, live, gid, cap):
        from spark_rapids_tpu.ops.common import sort_permutation

        K = self.K
        n_row = buffers[K].data.astype(jnp.float64)
        row_ok = live & (n_row > 0) & buffers[0].validity
        flat = cap * K
        vals = jnp.stack([b.data for b in buffers[:K]],
                         axis=1).reshape(flat)
        gid_f = jnp.repeat(gid, K)
        w_f = jnp.repeat(jnp.where(row_ok, n_row / K, 0.0), K)
        ok_f = w_f > 0
        rank = jnp.where(ok_f, 0, 1).astype(jnp.int64)
        key_v = jnp.where(ok_f, vals, jnp.inf)
        perm = sort_permutation(
            [gid_f.astype(jnp.int64), rank, key_v], flat)
        svals = jnp.take(key_v, perm)
        sgid = jnp.take(gid_f, perm)
        sw = jnp.take(w_f, perm)
        pos = jnp.arange(flat, dtype=jnp.int32)
        # segment ids live in [0, cap); the flattened domain only needs
        # cap segments
        outs, total = self._extract(svals, sgid, sw > 0, pos, cap,
                                    weights=sw)
        n = jnp.round(total).astype(jnp.int64)
        ok = n > 0
        cols = [DeviceColumn(double, o, ok) for o in outs]
        cols.append(DeviceColumn(long, n, jnp.ones((cap,), bool)))
        return cols

    def evaluate(self, buffers):
        K = self.K
        n = buffers[K].data
        rk = self.percentage * (K - 1)
        lo = int(np.floor(rk))
        hi = int(np.ceil(rk))
        frac = rk - lo
        vlo = buffers[lo].data
        vhi = buffers[hi].data
        data = vlo + (vhi - vlo) * frac
        return DeviceColumn(double, data, n > 0)
