"""Device-side ANSI overflow detection (GpuCast ANSI paths +
`arithmetic.scala` overflow checks, re-designed for XLA).

A traced program cannot raise data-dependently, so ANSI conditions are
computed as per-row boolean MASKS and reduced to one scalar per error
class inside a compiled check program; the host fetches the two bools
and raises `TpuArithmeticOverflow` / `TpuDivideByZero` before emitting
the batch (the reference's kernels throw from the CUDA stream sync —
same user-visible contract, different mechanism).

The checked set (device): integral add/subtract/multiply overflow,
negate/abs of MIN_VALUE, divide/remainder/pmod by zero, integral
narrowing casts, float->integral casts. String parsing casts and
decimal casts keep their CPU fallback under ANSI (plan/typesig.py),
where errors raise eagerly.

Null inputs never raise (Spark evaluates NULL, not an error), so every
mask is ANDed with operand validity.
"""

from __future__ import annotations

from typing import List, Tuple

import jax.numpy as jnp
import numpy as np

from spark_rapids_tpu.expr import arith as A
from spark_rapids_tpu.expr.cast import Cast
from spark_rapids_tpu.expr.core import EvalContext, Expression
from spark_rapids_tpu.sqltypes import (
    DecimalType,
    DoubleType,
    FloatType,
    IntegralType,
    StringType,
)

_ARITH = "arith"
_DIVZERO = "divzero"
_CAST = "cast"


def _is_int(dt) -> bool:
    return isinstance(dt, IntegralType) and not isinstance(dt, DecimalType)


def _node_checked(e: Expression) -> bool:
    if isinstance(e, (A.Add, A.Subtract, A.Multiply)):
        return _is_int(e.dtype)
    if isinstance(e, (A.UnaryMinus, A.Abs)):
        return _is_int(e.dtype)
    if isinstance(e, (A.Divide, A.IntegralDivide, A.Remainder, A.Pmod)):
        return True
    if isinstance(e, Cast):
        frm, to = e.children[0].dtype, e.to
        if isinstance(frm, (FloatType, DoubleType)) and _is_int(to):
            return True
        if _is_int(frm) and _is_int(to) and (
                np.iinfo(to.np_dtype).max < np.iinfo(frm.np_dtype).max):
            return True
    return False


def has_ansi_checks(e: Expression) -> bool:
    """Static: does this tree contain any device-checked ANSI node?"""
    if _node_checked(e):
        return True
    return any(has_ansi_checks(c) for c in e.children)


def _both_valid(lc, rc) -> jnp.ndarray:
    return lc.validity & rc.validity


def _node_masks(e: Expression, ctx: EvalContext
                ) -> List[Tuple[str, jnp.ndarray]]:
    if isinstance(e, (A.Add, A.Subtract, A.Multiply)) and _is_int(e.dtype):
        out_np = e.dtype.np_dtype
        lc, rc = e.left.eval(ctx), e.right.eval(ctx)
        a = lc.data.astype(out_np)
        b = rc.data.astype(out_np)
        valid = _both_valid(lc, rc)
        if isinstance(e, A.Multiply):
            res = a * b
            mn = jnp.array(np.iinfo(out_np).min, out_np)
            safe = jnp.where(a == 0, jnp.ones_like(a), a)
            ovf = (a != 0) & ((res // safe != b) | ((a == -1) & (b == mn)))
        elif isinstance(e, A.Subtract):
            res = a - b
            ovf = ((a ^ b) & (a ^ res)) < 0
        else:
            res = a + b
            ovf = ((a ^ res) & (b ^ res)) < 0
        return [(_ARITH, valid & ovf)]
    if isinstance(e, (A.UnaryMinus, A.Abs)) and _is_int(e.dtype):
        c = e.children[0].eval(ctx)
        mn = jnp.array(np.iinfo(e.dtype.np_dtype).min, e.dtype.np_dtype)
        return [(_ARITH, c.validity &
                 (c.data.astype(e.dtype.np_dtype) == mn))]
    if isinstance(e, (A.Divide, A.IntegralDivide, A.Remainder, A.Pmod)):
        lc, rc = e.children[0].eval(ctx), e.children[1].eval(ctx)
        zero = rc.data == 0 if rc.data.ndim == 1 else jnp.all(
            rc.data == 0, axis=-1)
        return [(_DIVZERO, _both_valid(lc, rc) & zero)]
    if isinstance(e, Cast):
        frm, to = e.children[0].dtype, e.to
        if isinstance(frm, (FloatType, DoubleType)) and _is_int(to):
            c = e.children[0].eval(ctx)
            info = np.iinfo(to.np_dtype)
            f = c.data
            bad = jnp.isnan(f) | (f < float(info.min)) | \
                (f > float(info.max))
            return [(_CAST, c.validity & bad)]
        if _is_int(frm) and _is_int(to) and (
                np.iinfo(to.np_dtype).max < np.iinfo(frm.np_dtype).max):
            c = e.children[0].eval(ctx)
            info = np.iinfo(to.np_dtype)
            v = c.data.astype(jnp.int64)
            return [(_CAST, c.validity &
                     ((v < info.min) | (v > info.max)))]
    return []


def overflow_masks(e: Expression, ctx: EvalContext
                   ) -> List[Tuple[str, jnp.ndarray]]:
    """Recursive: (error_kind, per-row mask) for every checked node.
    Short-circuit semantics (CaseWhen/If/Coalesce branches) are
    conservative: a branch that would not be evaluated can still
    raise — the same trade the reference's ANSI device kernels make
    for vectorized evaluation."""
    out = _node_masks(e, ctx)
    for c in e.children:
        out.extend(overflow_masks(c, ctx))
    return out


def flags_vec(exprs: List[Expression], batch, live=None) -> jnp.ndarray:
    """Traced reduction of every checked node's mask to one (3,) bool
    vector [arith, divzero, cast] over `live` rows. The fused executor
    accumulates these vectors through its overflow-flag channel
    (exec/fused.py) so ANSI costs zero extra host roundtrips."""
    ctx = EvalContext(batch)
    if live is None:
        live = batch.live_mask()
    flags = {_ARITH: jnp.zeros((), bool),
             _DIVZERO: jnp.zeros((), bool),
             _CAST: jnp.zeros((), bool)}
    for e in exprs:
        for kind, mask in overflow_masks(e, ctx):
            flags[kind] = flags[kind] | jnp.any(mask & live)
    return jnp.stack([flags[_ARITH], flags[_DIVZERO], flags[_CAST]])


def check_fn(exprs: List[Expression]):
    """Build the jittable check program: batch -> (arith_err, div_err)
    scalars. Caller fetches and raises."""

    def run(batch):
        v = flags_vec(exprs, batch)
        return v[0], v[1], v[2]

    return run


def raise_host(arith: bool, div: bool, cast: bool) -> None:
    """Raise the ANSI error for already-fetched host flags."""
    from spark_rapids_tpu.runtime.errors import (
        TpuArithmeticOverflow,
        TpuCastError,
        TpuDivideByZero,
    )

    if arith:
        raise TpuArithmeticOverflow(
            "[ARITHMETIC_OVERFLOW] overflow in ANSI mode; set "
            "spark.sql.ansi.enabled=false to wrap instead")
    if div:
        raise TpuDivideByZero(
            "[DIVIDE_BY_ZERO] division by zero in ANSI mode")
    if cast:
        raise TpuCastError(
            "[CAST_OVERFLOW] cast overflow in ANSI mode")


def raise_if_set(flags) -> None:
    from spark_rapids_tpu.obs import telemetry

    arith, div, cast = (bool(x) for x in telemetry.ledgered_get(
        flags, "ansi.flags"))
    raise_host(arith, div, cast)
