"""Struct expressions — GetStructField / CreateNamedStruct over
struct-of-arrays device columns (DeviceColumn.children; the cuDF
nested-column role, reference `complexTypeExtractors` /
`GpuCreateNamedStruct` rules in GpuOverrides.scala).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import StructField, StructType


class GetStructField(Expression):
    """struct.field extraction; a parent-null row yields a null field
    (Spark GetStructField semantics)."""

    def __init__(self, child: Expression, name: str):
        super().__init__([child])
        self.name = name

    @property
    def _ordinal(self) -> int:
        return self.children[0].dtype.field_index(self.name)

    @property
    def dtype(self):
        return self.children[0].dtype.fields[self._ordinal].dataType

    @property
    def nullable(self):
        return True

    def key(self):
        return ("get_struct_field", self.name, self.children[0].key())

    def eval(self, ctx) -> DeviceColumn:
        col = self.children[0].eval(ctx)
        kid = col.children[self._ordinal]
        return kid.with_validity(kid.validity & col.validity)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.name}"


class CreateNamedStruct(Expression):
    """struct(col1, col2, ...) — field expressions to a struct column.
    Never null itself, like Spark's CreateNamedStruct."""

    def __init__(self, names: List[str], exprs: List[Expression]):
        assert len(names) == len(exprs)
        super().__init__(list(exprs))
        self.names = list(names)

    @property
    def dtype(self):
        return StructType([
            StructField(n, e.dtype, e.nullable)
            for n, e in zip(self.names, self.children)])

    @property
    def nullable(self):
        return False

    def key(self):
        return ("create_named_struct", tuple(self.names),
                tuple(c.key() for c in self.children))

    def eval(self, ctx) -> DeviceColumn:
        kids = [e.eval(ctx) for e in self.children]
        # struct() with no fields is legal Spark; size from the batch
        cap = kids[0].capacity if kids else ctx.batch.capacity
        return DeviceColumn(
            self.dtype, jnp.zeros((cap,), jnp.int8),
            jnp.ones((cap,), jnp.bool_), children=kids)

    def __repr__(self):
        return "struct(" + ", ".join(self.names) + ")"
