"""Struct expressions — GetStructField / CreateNamedStruct over
struct-of-arrays device columns (DeviceColumn.children; the cuDF
nested-column role, reference `complexTypeExtractors` /
`GpuCreateNamedStruct` rules in GpuOverrides.scala).
"""

from __future__ import annotations

from typing import List

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import StructField, StructType


class GetStructField(Expression):
    """struct.field extraction; a parent-null row yields a null field
    (Spark GetStructField semantics)."""

    def __init__(self, child: Expression, name: str):
        super().__init__([child])
        self.name = name

    @property
    def _ordinal(self) -> int:
        return self.children[0].dtype.field_index(self.name)

    @property
    def dtype(self):
        return self.children[0].dtype.fields[self._ordinal].dataType

    @property
    def nullable(self):
        return True

    def key(self):
        return ("get_struct_field", self.name, self.children[0].key())

    def eval(self, ctx) -> DeviceColumn:
        col = self.children[0].eval(ctx)
        kid = col.children[self._ordinal]
        return kid.with_validity(kid.validity & col.validity)

    def __repr__(self):
        return f"{self.children[0]!r}.{self.name}"


class CreateNamedStruct(Expression):
    """struct(col1, col2, ...) — field expressions to a struct column.
    Never null itself, like Spark's CreateNamedStruct — unless
    `valid_from` is given (a final extra child): then the struct's
    top-level validity copies that child's validity, which is how the
    struct-key grouping rewrite (plan/struct_keys.py) rebuilds a
    possibly-null struct key from its expanded NullGate column."""

    def __init__(self, names: List[str], exprs: List[Expression],
                 valid_from: Expression = None):
        assert len(names) == len(exprs)
        kids = list(exprs) + ([valid_from] if valid_from is not None
                              else [])
        super().__init__(kids)
        self.names = list(names)
        self._has_gate = valid_from is not None

    @property
    def _fields(self):
        return self.children[:-1] if self._has_gate else self.children

    @property
    def dtype(self):
        return StructType([
            StructField(n, e.dtype, e.nullable)
            for n, e in zip(self.names, self._fields)])

    @property
    def nullable(self):
        return self._has_gate

    def key(self):
        return ("create_named_struct", tuple(self.names), self._has_gate,
                tuple(c.key() for c in self.children))

    def eval(self, ctx) -> DeviceColumn:
        kids = [e.eval(ctx) for e in self._fields]
        # struct() with no fields is legal Spark; size from the batch
        cap = kids[0].capacity if kids else ctx.batch.capacity
        validity = (self.children[-1].eval(ctx).validity
                    if self._has_gate else jnp.ones((cap,), jnp.bool_))
        return DeviceColumn(
            self.dtype, jnp.zeros((cap,), jnp.int8),
            validity, children=kids)

    def __repr__(self):
        return "struct(" + ", ".join(self.names) + ")"


class NullGate(Expression):
    """Boolean key column that is TRUE where the child is non-null and
    NULL where it is null — turns a struct key's TOP-LEVEL nullability
    into an orderable primitive key: as a join key, a null struct never
    matches (Spark EqualTo null propagation); as a grouping key, null
    structs group together, distinct from a non-null struct whose
    fields are all null (plan/struct_keys.py expansion)."""

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import boolean

        return boolean

    @property
    def nullable(self):
        return self.children[0].nullable

    def key(self):
        return ("null_gate", self.children[0].key())

    def eval(self, ctx) -> DeviceColumn:
        c = self.children[0].eval(ctx)
        return DeviceColumn(self.dtype,
                            jnp.ones((c.capacity,), jnp.bool_),
                            c.validity)

    def __repr__(self):
        return f"null_gate({self.children[0]!r})"
