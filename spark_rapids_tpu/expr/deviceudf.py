"""User device UDFs — the RapidsUDF analog (reference
sql-plugin-api/.../RapidsUDF.java:22-68: a user function that evaluates
COLUMNAR on device; GpuUserDefinedFunction.scala:33-40 runs it inside
the operator's device pipeline).

Here the user supplies a function over jnp arrays:

    def my_fn(values, validity):        # [cap] arrays
        return values * 2 + 1, validity

and the expression evaluates it INSIDE the enclosing jitted operator —
XLA fuses it with the rest of the projection, which is strictly better
than the reference's separately-launched UDF kernel."""

from __future__ import annotations

from typing import Callable, List

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import DataType


class DeviceUDF(Expression):
    """fn(values..., validities...) -> (values, validity); traced into
    the enclosing XLA program."""

    def __init__(self, fn: Callable, return_type: DataType,
                 children: List[Expression]):
        super().__init__(children)
        self.fn = fn
        self._dtype = return_type

    @property
    def dtype(self):
        return self._dtype

    @property
    def nullable(self):
        return True

    def key(self):
        # id(fn) is stable for the process lifetime, which is the
        # lifetime of the jit cache
        return ("device_udf", id(self.fn),
                tuple(c.key() for c in self.children))

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        out = self.fn(*[c.data for c in cols],
                      *[c.validity for c in cols])
        if isinstance(out, tuple):
            data, validity = out
        else:
            data = out
            validity = cols[0].validity if cols else None
        return DeviceColumn(self._dtype, data, validity)
