"""JSON expressions — GpuGetJsonObject / JSONUtils role. v1 evaluates on
the host (the planner's type checks route the operator to the CPU path
with a tagged reason); a device byte-level JSON scanner in the
stringcast/regex DFA style is the follow-up."""

from __future__ import annotations

import json
import re
from typing import List

from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes.datatypes import string as string_t

_STEP = re.compile(r"\.([A-Za-z_][A-Za-z0-9_]*)|\[(\d+)\]")


def parse_json_path(path: str) -> List[object]:
    """'$.a.b[0]' -> ['a', 'b', 0]; raises on malformed paths."""
    if not path.startswith("$"):
        raise ValueError(f"JSON path must start with $: {path!r}")
    steps: List[object] = []
    pos = 1
    while pos < len(path):
        m = _STEP.match(path, pos)
        if not m:
            raise ValueError(f"bad JSON path {path!r} at {pos}")
        steps.append(m.group(1) if m.group(1) is not None
                     else int(m.group(2)))
        pos = m.end()
    return steps


def extract_json(doc: str, steps: List[object]):
    """Spark get_json_object semantics: invalid JSON / missing path ->
    null; scalar results unquoted, nested results re-serialized."""
    try:
        v = json.loads(doc)
    except (ValueError, TypeError):
        return None
    for s in steps:
        if isinstance(s, int):
            if not isinstance(v, list) or s >= len(v):
                return None
            v = v[s]
        else:
            if not isinstance(v, dict) or s not in v:
                return None
            v = v[s]
    if v is None:
        return None
    if isinstance(v, (dict, list)):
        return json.dumps(v, separators=(",", ":"))
    if isinstance(v, bool):
        return "true" if v else "false"
    return str(v)


class GetJsonObject(Expression):
    def __init__(self, child: Expression, path: str):
        super().__init__([child])
        self.path = path
        self.steps = parse_json_path(path)

    @property
    def dtype(self):
        return string_t

    @property
    def nullable(self):
        return True

    def key(self):
        return ("get_json_object", self.path, self.children[0].key())


class ParseUrl(Expression):
    """parse_url(url, part[, key]) — GpuParseUrl / ParseURI JNI role;
    host-evaluated in v1 with Spark's part names (PROTOCOL, HOST, PATH,
    QUERY, REF, FILE, AUTHORITY, USERINFO)."""

    PARTS = ("PROTOCOL", "HOST", "PATH", "QUERY", "REF", "FILE",
             "AUTHORITY", "USERINFO")

    def __init__(self, child: Expression, part: str, key=None):
        super().__init__([child])
        if part not in self.PARTS:
            raise ValueError(f"parse_url part {part!r}")
        self.part = part
        self.query_key = key

    @property
    def dtype(self):
        return string_t

    @property
    def nullable(self):
        return True

    def key(self):
        return ("parse_url", self.part, self.query_key,
                self.children[0].key())


def extract_url(url: str, part: str, key=None):
    from urllib.parse import urlsplit

    try:
        u = urlsplit(url)
    except ValueError:
        return None
    if not u.scheme or "://" not in url:
        return None
    if part == "PROTOCOL":
        return u.scheme or None
    if part == "HOST":
        # java.net.URI preserves host case and IPv6 brackets (urllib's
        # .hostname lowercases): extract raw from the netloc
        host = u.netloc.rsplit("@", 1)[-1]
        if host.startswith("["):
            end = host.find("]")
            host = host[:end + 1] if end >= 0 else host
        else:
            host = host.split(":", 1)[0]
        return host or None
    if part == "PATH":
        return u.path or None
    if part == "QUERY":
        if key is not None:
            # Spark extracts the RAW substring (no URL decoding, blank
            # values preserved)
            m = re.search(r"(?:^|&)" + re.escape(key) + r"=([^&]*)",
                          u.query)
            return m.group(1) if m else None
        return u.query or None
    if part == "REF":
        return u.fragment or None
    if part == "FILE":
        return (u.path + ("?" + u.query if u.query else "")) or None
    if part == "AUTHORITY":
        return u.netloc or None
    if part == "USERINFO":
        if u.username is None and u.password is None:
            return None
        return (u.username or "") + (
            ":" + u.password if u.password is not None else "")
    return None
