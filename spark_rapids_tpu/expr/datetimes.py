"""Date/time expressions (UTC session timezone, like the reference's
default device path; non-UTC zones there require GpuTimeZoneDB, here a
planned extension via a device transition table).

Date math uses Howard Hinnant's civil-from-days algorithm — pure integer
ops, fully vectorized on the VPU.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import DateType, TimestampType
from spark_rapids_tpu.sqltypes.datatypes import integer

_US_PER_DAY = 86_400_000_000


def civil_from_days(z: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray,
                                             jnp.ndarray]:
    """days-since-epoch -> (year, month, day), proleptic Gregorian."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _days_of(col: DeviceColumn) -> jnp.ndarray:
    if isinstance(col.dtype, TimestampType):
        return jnp.floor_divide(col.data, _US_PER_DAY)
    return col.data.astype(jnp.int64)


class _DatePart(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    def _part(self, y, m, d):
        raise NotImplementedError

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        y, m, d = civil_from_days(_days_of(c))
        return DeviceColumn(integer, self._part(y, m, d), c.validity)


class Year(_DatePart):
    def _part(self, y, m, d):
        return y


class Month(_DatePart):
    def _part(self, y, m, d):
        return m


class DayOfMonth(_DatePart):
    def _part(self, y, m, d):
        return d


class _TimePart(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    divisor = 1
    modulus = 1

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        us_in_day = c.data - jnp.floor_divide(c.data, _US_PER_DAY) * \
            _US_PER_DAY
        val = (us_in_day // self.divisor) % self.modulus
        return DeviceColumn(integer, val.astype(jnp.int32), c.validity)


class Hour(_TimePart):
    divisor = 3_600_000_000
    modulus = 24


class Minute(_TimePart):
    divisor = 60_000_000
    modulus = 60


class Second(_TimePart):
    divisor = 1_000_000
    modulus = 60
