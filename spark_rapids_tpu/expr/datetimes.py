"""Date/time expressions.

Non-UTC session timezones rebase through the device transition table in
ops/tzdb.py (the GpuTimeZoneDB role; reference GpuTimeZoneDB usage in
GpuCast.scala and datetime expression rules in GpuOverrides.scala) —
tz-sensitive expressions carry a `tz` zone id that the session stamps
at resolution time and that participates in every jit cache key.

Date math uses Howard Hinnant's civil-from-days algorithm — pure integer
ops, fully vectorized on the VPU.
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression, Literal
from spark_rapids_tpu.ops import tzdb
from spark_rapids_tpu.sqltypes import DateType, StringType, TimestampType
from spark_rapids_tpu.sqltypes.datatypes import (
    date as date_t,
    double,
    integer,
    long,
    timestamp as timestamp_t,
)

_US_PER_DAY = 86_400_000_000
_US_PER_SEC = 1_000_000


class TzAware:
    """Mixin: expression whose semantics depend on the session timezone.
    `tz` is stamped by the session at resolution time and is part of the
    jit key so each (program, zone) compiles once."""

    tz: str = "UTC"

    def key(self):
        return (type(self).__name__, self.tz,
                tuple(c.key() for c in self.children))


def civil_from_days(z: jnp.ndarray) -> Tuple[jnp.ndarray, jnp.ndarray,
                                             jnp.ndarray]:
    """days-since-epoch -> (year, month, day), proleptic Gregorian."""
    z = z.astype(jnp.int64) + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    y = jnp.where(m <= 2, y + 1, y)
    return y.astype(jnp.int32), m.astype(jnp.int32), d.astype(jnp.int32)


def days_from_civil(y, m, d):
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return (era * 146097 + doe - 719468).astype(jnp.int32)


def _local_us(col: DeviceColumn, tz: str) -> jnp.ndarray:
    """Timestamp column -> local wall-clock epoch-us."""
    if tzdb.is_utc(tz):
        return col.data
    return tzdb.utc_to_local(col.data, tz)


def _days_of(col: DeviceColumn, tz: str = "UTC") -> jnp.ndarray:
    if isinstance(col.dtype, TimestampType):
        return jnp.floor_divide(_local_us(col, tz), _US_PER_DAY)
    return col.data.astype(jnp.int64)


class _DatePart(TzAware, Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    def _part(self, y, m, d):
        raise NotImplementedError

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        y, m, d = civil_from_days(_days_of(c, self.tz))
        return DeviceColumn(integer, self._part(y, m, d), c.validity)


class Year(_DatePart):
    def _part(self, y, m, d):
        return y


class Month(_DatePart):
    def _part(self, y, m, d):
        return m


class DayOfMonth(_DatePart):
    def _part(self, y, m, d):
        return d


class _TimePart(TzAware, Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    divisor = 1
    modulus = 1

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        us = _local_us(c, self.tz)
        us_in_day = us - jnp.floor_divide(us, _US_PER_DAY) * _US_PER_DAY
        val = (us_in_day // self.divisor) % self.modulus
        return DeviceColumn(integer, val.astype(jnp.int32), c.validity)


class Hour(_TimePart):
    divisor = 3_600_000_000
    modulus = 24


class Minute(_TimePart):
    divisor = 60_000_000
    modulus = 60


class Second(_TimePart):
    divisor = 1_000_000
    modulus = 60


# ------------------------------------------------------- calendar parts


class DayOfWeek(_DatePart):
    """Spark dayofweek: 1 = Sunday .. 7 = Saturday."""

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        d = _days_of(c, self.tz)
        return DeviceColumn(integer,
                            ((d + 4) % 7 + 1).astype(jnp.int32),
                            c.validity)


class WeekDay(_DatePart):
    """Spark weekday: 0 = Monday .. 6 = Sunday."""

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        d = _days_of(c, self.tz)
        return DeviceColumn(integer, ((d + 3) % 7).astype(jnp.int32),
                            c.validity)


class DayOfYear(_DatePart):
    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        d = _days_of(c, self.tz)
        y, _, _ = civil_from_days(d)
        jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        return DeviceColumn(integer, (d - jan1 + 1).astype(jnp.int32),
                            c.validity)


class WeekOfYear(_DatePart):
    """ISO-8601 week number (the week containing Thursday)."""

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        d = _days_of(c, self.tz)
        thu = d - (d + 3) % 7 + 3
        y, _, _ = civil_from_days(thu)
        jan1 = days_from_civil(y, jnp.ones_like(y), jnp.ones_like(y))
        week = (thu - jan1) // 7 + 1
        return DeviceColumn(integer, week.astype(jnp.int32), c.validity)


class Quarter(_DatePart):
    def _part(self, y, m, d):
        return (m - 1) // 3 + 1


def _month_len(y, m):
    nxt_m = jnp.where(m == 12, 1, m + 1)
    nxt_y = jnp.where(m == 12, y + 1, y)
    one = jnp.ones_like(m)
    return (days_from_civil(nxt_y, nxt_m, one)
            - days_from_civil(y, m, one))


class LastDay(_DatePart):
    @property
    def dtype(self):
        return date_t

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        y, m, _ = civil_from_days(_days_of(c, self.tz))
        one = jnp.ones_like(m)
        nd = days_from_civil(y, m, one) + _month_len(y, m) - 1
        return DeviceColumn(date_t, nd.astype(jnp.int32), c.validity)


# ------------------------------------------------------- date arithmetic


class DateAdd(Expression):
    """date_add(date, n). DateSub flips the sign."""

    _sign = 1

    def __init__(self, date: Expression, n: Expression):
        super().__init__([date, n])

    @property
    def dtype(self):
        return date_t

    def eval(self, ctx):
        d = self.children[0].eval(ctx)
        n = self.children[1].eval(ctx)
        days = d.data.astype(jnp.int32) \
            + self._sign * n.data.astype(jnp.int32)
        from spark_rapids_tpu.expr.core import binary_validity

        return DeviceColumn(date_t, days, binary_validity(d, n))


class DateSub(DateAdd):
    _sign = -1


class DateDiff(Expression):
    """datediff(end, start) in days."""

    def __init__(self, end: Expression, start: Expression):
        super().__init__([end, start])

    @property
    def dtype(self):
        return integer

    def eval(self, ctx):
        e = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        from spark_rapids_tpu.expr.core import binary_validity

        return DeviceColumn(
            integer,
            (e.data.astype(jnp.int32) - s.data.astype(jnp.int32)),
            binary_validity(e, s))


class AddMonths(Expression):
    def __init__(self, date: Expression, n: Expression):
        super().__init__([date, n])

    @property
    def dtype(self):
        return date_t

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        n = self.children[1].eval(ctx)
        y, m, d = civil_from_days(c.data.astype(jnp.int64))
        m0 = y * 12 + (m - 1) + n.data.astype(jnp.int32)
        ny = jnp.floor_divide(m0, 12)
        nm = m0 - ny * 12 + 1
        nd = jnp.minimum(d, _month_len(ny, nm))
        from spark_rapids_tpu.expr.core import binary_validity

        return DeviceColumn(date_t, days_from_civil(ny, nm, nd),
                            binary_validity(c, n))


class MonthsBetween(TzAware, Expression):
    """months_between(end, start[, roundOff]) — Spark's 31-day-month
    fractional rule: integral when both are the same day-of-month or
    both are month-ends, else day+time difference / 31."""

    def __init__(self, end: Expression, start: Expression,
                 round_off: bool = True):
        super().__init__([end, start])
        self.round_off = round_off

    @property
    def dtype(self):
        return double

    def key(self):
        return ("months_between", self.tz, self.round_off,
                tuple(c.key() for c in self.children))

    def _fields(self, col):
        if isinstance(col.dtype, TimestampType):
            us = _local_us(col, self.tz)
        else:
            us = col.data.astype(jnp.int64) * _US_PER_DAY
        days = jnp.floor_divide(us, _US_PER_DAY)
        tod = (us - days * _US_PER_DAY).astype(jnp.float64) / _US_PER_SEC
        y, m, d = civil_from_days(days)
        return y, m, d, tod

    def eval(self, ctx):
        e = self.children[0].eval(ctx)
        s = self.children[1].eval(ctx)
        y1, m1, d1, t1 = self._fields(e)
        y2, m2, d2, t2 = self._fields(s)
        months = ((y1 - y2) * 12 + (m1 - m2)).astype(jnp.float64)
        last1 = d1 == _month_len(y1, m1)
        last2 = d2 == _month_len(y2, m2)
        integral = (d1 == d2) | (last1 & last2)
        sec1 = d1.astype(jnp.float64) * 86400.0 + t1
        sec2 = d2.astype(jnp.float64) * 86400.0 + t2
        frac = (sec1 - sec2) / (31.0 * 86400.0)
        out = jnp.where(integral, months, months + frac)
        if self.round_off:
            out = jnp.round(out * 1e8) / 1e8
        from spark_rapids_tpu.expr.core import binary_validity

        return DeviceColumn(double, out, binary_validity(e, s))


_DAY_NAMES = {
    "MO": 1, "MON": 1, "MONDAY": 1, "TU": 2, "TUE": 2, "TUESDAY": 2,
    "WE": 3, "WED": 3, "WEDNESDAY": 3, "TH": 4, "THU": 4, "THURSDAY": 4,
    "FR": 5, "FRI": 5, "FRIDAY": 5, "SA": 6, "SAT": 6, "SATURDAY": 6,
    "SU": 7, "SUN": 7, "SUNDAY": 7,
}


class NextDay(Expression):
    """next_day(date, 'Mon'): first date strictly after `date` that
    falls on the given weekday; invalid day name -> null."""

    def __init__(self, date: Expression, day_name: str):
        super().__init__([date])
        self.target = _DAY_NAMES.get(str(day_name).strip().upper())

    @property
    def dtype(self):
        return date_t

    @property
    def nullable(self):
        return True

    def key(self):
        return ("next_day", self.target, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if self.target is None:
            return DeviceColumn(date_t, jnp.zeros_like(c.data),
                                jnp.zeros(c.data.shape, bool))
        d = c.data.astype(jnp.int32)
        # ISO dow: Monday=1..Sunday=7; 1970-01-01 is Thursday(4)
        dow = (d + 3) % 7 + 1
        delta = (self.target - dow + 7) % 7
        delta = jnp.where(delta == 0, 7, delta)
        return DeviceColumn(date_t, d + delta, c.validity)


# ------------------------------------------------------------ truncation

_TRUNC_DATE_FMTS = {
    "YEAR": "year", "YYYY": "year", "YY": "year",
    "QUARTER": "quarter", "MONTH": "month", "MON": "month",
    "MM": "month", "WEEK": "week",
}
_TRUNC_TS_FMTS = dict(_TRUNC_DATE_FMTS, **{
    "DAY": "day", "DD": "day", "HOUR": "hour", "MINUTE": "minute",
    "SECOND": "second",
})


def _trunc_days(days, unit):
    y, m, d = civil_from_days(days)
    one = jnp.ones_like(m)
    if unit == "year":
        return days_from_civil(y, one, one)
    if unit == "quarter":
        qm = ((m - 1) // 3) * 3 + 1
        return days_from_civil(y, qm, one)
    if unit == "month":
        return days_from_civil(y, m, one)
    if unit == "week":  # Monday start
        return (days - (days + 3) % 7).astype(jnp.int32)
    raise ValueError(unit)


class TruncDate(Expression):
    """trunc(date, fmt) -> date; unknown fmt -> null (Spark)."""

    def __init__(self, date: Expression, fmt: str):
        super().__init__([date])
        self.unit = _TRUNC_DATE_FMTS.get(str(fmt).strip().upper())

    @property
    def dtype(self):
        return date_t

    @property
    def nullable(self):
        return True

    def key(self):
        return ("trunc_date", self.unit, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if self.unit is None:
            return DeviceColumn(date_t, jnp.zeros_like(c.data),
                                jnp.zeros(c.data.shape, bool))
        days = _trunc_days(c.data.astype(jnp.int64), self.unit)
        return DeviceColumn(date_t, days.astype(jnp.int32), c.validity)


class DateTrunc(TzAware, Expression):
    """date_trunc(fmt, timestamp) -> timestamp, truncated in the
    session zone's wall-clock then rebased to UTC."""

    def __init__(self, fmt: str, ts: Expression):
        super().__init__([ts])
        self.unit = _TRUNC_TS_FMTS.get(str(fmt).strip().upper())

    @property
    def dtype(self):
        return timestamp_t

    @property
    def nullable(self):
        return True

    def key(self):
        return ("date_trunc", self.unit, self.tz,
                self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if self.unit is None:
            return DeviceColumn(timestamp_t, jnp.zeros_like(c.data),
                                jnp.zeros(c.data.shape, bool))
        us = _local_us(c, self.tz)
        if self.unit == "second":
            out = jnp.floor_divide(us, _US_PER_SEC) * _US_PER_SEC
        elif self.unit == "minute":
            out = jnp.floor_divide(us, 60 * _US_PER_SEC) \
                * (60 * _US_PER_SEC)
        elif self.unit == "hour":
            out = jnp.floor_divide(us, 3600 * _US_PER_SEC) \
                * (3600 * _US_PER_SEC)
        elif self.unit == "day":
            out = jnp.floor_divide(us, _US_PER_DAY) * _US_PER_DAY
        else:
            days = _trunc_days(jnp.floor_divide(us, _US_PER_DAY),
                               self.unit)
            out = days.astype(jnp.int64) * _US_PER_DAY
        if not tzdb.is_utc(self.tz):
            out = tzdb.local_to_utc(out, self.tz)
        return DeviceColumn(timestamp_t, out, c.validity)


# ------------------------------------------------------ epoch conversion


class UnixTimestamp(Expression):
    """unix_timestamp(ts) -> seconds since epoch (long)."""

    def __init__(self, ts: Expression):
        super().__init__([ts])

    @property
    def dtype(self):
        return long

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(
            long, jnp.floor_divide(c.data, _US_PER_SEC), c.validity)


class SecondsToTimestamp(Expression):
    """timestamp_seconds(col) — numeric seconds -> timestamp."""

    def __init__(self, secs: Expression):
        super().__init__([secs])

    @property
    def dtype(self):
        return timestamp_t

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if jnp.issubdtype(c.data.dtype, jnp.floating):
            us = jnp.round(c.data * _US_PER_SEC).astype(jnp.int64)
        else:
            us = c.data.astype(jnp.int64) * _US_PER_SEC
        return DeviceColumn(timestamp_t, us, c.validity)


class MakeDate(Expression):
    def __init__(self, y: Expression, m: Expression, d: Expression):
        super().__init__([y, m, d])

    @property
    def dtype(self):
        return date_t

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        y = self.children[0].eval(ctx)
        m = self.children[1].eval(ctx)
        d = self.children[2].eval(ctx)
        yy = y.data.astype(jnp.int32)
        mm = m.data.astype(jnp.int32)
        dd = d.data.astype(jnp.int32)
        ok = ((mm >= 1) & (mm <= 12) & (dd >= 1)
              & (dd <= _month_len(yy, jnp.clip(mm, 1, 12))))
        days = days_from_civil(yy, jnp.clip(mm, 1, 12),
                               jnp.clip(dd, 1, 31))
        return DeviceColumn(
            date_t, days,
            y.validity & m.validity & d.validity & ok)


class FromUtcTimestamp(Expression):
    """from_utc_timestamp(ts, zone): reinterpret a UTC instant as the
    given zone's wall clock (explicit zone, not the session zone)."""

    _to_utc = False

    def __init__(self, ts: Expression, zone: str):
        super().__init__([ts])
        self.zone = str(zone)

    @property
    def dtype(self):
        return timestamp_t

    def key(self):
        return (type(self).__name__, self.zone,
                self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if self._to_utc:
            out = tzdb.local_to_utc(c.data, self.zone)
        else:
            out = tzdb.utc_to_local(c.data, self.zone)
        return DeviceColumn(timestamp_t, out, c.validity)


class ToUtcTimestamp(FromUtcTimestamp):
    _to_utc = True


# ------------------------------------------------------------ formatting

_FMT_TOKENS = ("yyyy", "MM", "dd", "HH", "mm", "ss", "SSS")


def _tokenize_format(fmt: str):
    """Java SimpleDateFormat subset -> [(kind, text)] or None if the
    pattern uses tokens outside the supported set."""
    out = []
    i = 0
    while i < len(fmt):
        for tok in _FMT_TOKENS:
            if fmt.startswith(tok, i):
                out.append(("tok", tok))
                i += len(tok)
                break
        else:
            ch = fmt[i]
            if ch.isalpha():
                return None  # unsupported pattern letter
            out.append(("lit", ch))
            i += 1
    return out


class DateFormat(TzAware, Expression):
    """date_format(ts, fmt) for the fixed-width token subset
    yyyy/MM/dd/HH/mm/ss/SSS (+ literal separators); other patterns are
    tagged for CPU by the planner check below."""

    def __init__(self, ts: Expression, fmt: str):
        super().__init__([ts])
        self.fmt = str(fmt)
        self.tokens = _tokenize_format(self.fmt)

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import string as string_t

        return string_t

    def key(self):
        return ("date_format", self.fmt, self.tz,
                self.children[0].key())

    def device_supported(self):
        if self.tokens is None:
            return (f"date_format pattern {self.fmt!r} outside the "
                    "device token subset (yyyy MM dd HH mm ss SSS)")
        return None

    def eval(self, ctx):
        from spark_rapids_tpu.sqltypes.datatypes import string as string_t

        c = self.children[0].eval(ctx)
        if isinstance(c.dtype, TimestampType):
            us = _local_us(c, self.tz)
        else:
            us = c.data.astype(jnp.int64) * _US_PER_DAY
        days = jnp.floor_divide(us, _US_PER_DAY)
        in_day = us - days * _US_PER_DAY
        y, m, d = civil_from_days(days)
        vals = {
            "yyyy": (y.astype(jnp.int64), 4),
            "MM": (m.astype(jnp.int64), 2),
            "dd": (d.astype(jnp.int64), 2),
            "HH": (in_day // 3_600_000_000, 2),
            "mm": ((in_day // 60_000_000) % 60, 2),
            "ss": ((in_day // _US_PER_SEC) % 60, 2),
            "SSS": ((in_day // 1000) % 1000, 3),
        }
        width = sum(vals[t][1] if k == "tok" else 1
                    for k, t in self.tokens)
        mb = max(8, 1 << (width - 1).bit_length())
        n = c.data.shape[0]
        mat = jnp.zeros((n, mb), jnp.uint8)
        pos = 0
        for kind, t in self.tokens:
            if kind == "lit":
                mat = mat.at[:, pos].set(jnp.uint8(ord(t)))
                pos += 1
            else:
                v, w = vals[t]
                for j in range(w):
                    digit = (v // (10 ** (w - 1 - j))) % 10
                    mat = mat.at[:, pos].set(
                        (digit + ord("0")).astype(jnp.uint8))
                    pos += 1
        lengths = jnp.full((n,), jnp.int32(width))
        return DeviceColumn(string_t, mat, c.validity, lengths)


class FromUnixtime(DateFormat):
    """from_unixtime(secs[, fmt]) -> formatted string in the session
    zone (default 'yyyy-MM-dd HH:mm:ss')."""

    def __init__(self, secs: Expression,
                 fmt: str = "yyyy-MM-dd HH:mm:ss"):
        super().__init__(SecondsToTimestamp(secs), fmt)

    def key(self):
        return ("from_unixtime", self.fmt, self.tz,
                self.children[0].key())


class CurrentDate(TzAware, Expression):
    """Marker; physical planning pins it to ONE literal date per query
    (api/dataframe._pin_query_time, like Spark's QueryExecution)."""

    @property
    def dtype(self):
        return date_t

    @property
    def nullable(self):
        return False


class CurrentTimestamp(Expression):
    """Marker; pinned to one literal timestamp per query at physical
    planning time."""

    @property
    def dtype(self):
        return timestamp_t

    @property
    def nullable(self):
        return False
