"""Math, rounding, and bitwise expressions with Spark semantics.

Reference coverage: the math/bitwise slice of the ~218 expression rules
registered in `GpuOverrides.scala:920+` (Sqrt, Exp, Log*, trig family,
Pow, Round/BRound, Ceil/Floor, ShiftLeft/Right, BitwiseAnd/Or/Xor/Not,
Hex, Signum, ...). Each node emits jnp ops that fuse into the enclosing
operator's XLA program (VPU elementwise work).

Spark corner cases reproduced:
- log/log10/log2 return NULL (not NaN/-Inf) for input <= 0; log1p NULL
  for input <= -1 (Spark `Logarithm` non-ANSI behavior).
- sqrt(-x) is NaN (Java Math.sqrt).
- round() is HALF_UP, bround() HALF_EVEN (Spark BigDecimal modes).
- ceil/floor of fractional input return LongType.
- shift counts are masked to 5/6 bits (Java `<<`/`>>`/`>>>`).
"""

from __future__ import annotations

import math

import jax.numpy as jnp
from jax import lax

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import EvalContext, Expression, binary_validity
from spark_rapids_tpu.sqltypes import (
    DoubleType,
    FloatType,
    IntegralType,
    LongType,
)
from spark_rapids_tpu.sqltypes.datatypes import (
    double,
    integer,
    long,
    numeric_promotion,
    string as string_t,
)


class UnaryMath(Expression):
    """double -> double elementwise math (Java Math semantics)."""

    _fn = None  # staticmethod set by subclasses

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return double

    def eval(self, ctx: EvalContext) -> DeviceColumn:
        c = self.children[0].eval(ctx)
        x = c.data.astype(jnp.float64)
        return DeviceColumn(double, type(self)._fn(x), c.validity)


class Sqrt(UnaryMath):
    _fn = staticmethod(jnp.sqrt)


class Exp(UnaryMath):
    _fn = staticmethod(jnp.exp)


class Expm1(UnaryMath):
    _fn = staticmethod(jnp.expm1)


class Cbrt(UnaryMath):
    _fn = staticmethod(jnp.cbrt)


class Rint(UnaryMath):
    _fn = staticmethod(jnp.round)  # HALF_EVEN, Java Math.rint


class Signum(UnaryMath):
    _fn = staticmethod(lambda x: jnp.sign(x))


class Sin(UnaryMath):
    _fn = staticmethod(jnp.sin)


class Cos(UnaryMath):
    _fn = staticmethod(jnp.cos)


class Tan(UnaryMath):
    _fn = staticmethod(jnp.tan)


class Cot(UnaryMath):
    _fn = staticmethod(lambda x: 1.0 / jnp.tan(x))


class Asin(UnaryMath):
    _fn = staticmethod(jnp.arcsin)


class Acos(UnaryMath):
    _fn = staticmethod(jnp.arccos)


class Atan(UnaryMath):
    _fn = staticmethod(jnp.arctan)


class Sinh(UnaryMath):
    _fn = staticmethod(jnp.sinh)


class Cosh(UnaryMath):
    _fn = staticmethod(jnp.cosh)


class Tanh(UnaryMath):
    _fn = staticmethod(jnp.tanh)


class Asinh(UnaryMath):
    _fn = staticmethod(jnp.arcsinh)


class Acosh(UnaryMath):
    _fn = staticmethod(jnp.arccosh)


class Atanh(UnaryMath):
    _fn = staticmethod(jnp.arctanh)


class ToDegrees(UnaryMath):
    _fn = staticmethod(lambda x: x * (180.0 / math.pi))


class ToRadians(UnaryMath):
    _fn = staticmethod(lambda x: x * (math.pi / 180.0))


class _NullDomainLog(Expression):
    """Log family: out-of-domain input -> NULL (Spark non-ANSI)."""

    _bound = 0.0  # input must be strictly greater than this

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return double

    @property
    def nullable(self):
        return True

    def _compute(self, x):
        raise NotImplementedError

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        x = c.data.astype(jnp.float64)
        # NULL only when input <= bound; NaN input stays NaN (Java check
        # `input <= 0` is false for NaN)
        out_of_domain = x <= self._bound
        safe = jnp.where(out_of_domain, 1.0, x)
        return DeviceColumn(double, self._compute(safe),
                            c.validity & ~out_of_domain)


class Log(_NullDomainLog):
    def _compute(self, x):
        return jnp.log(x)


class Log10(_NullDomainLog):
    def _compute(self, x):
        return jnp.log10(x)


class Log2(_NullDomainLog):
    def _compute(self, x):
        return jnp.log2(x)


class Log1p(_NullDomainLog):
    _bound = -1.0

    def _compute(self, x):
        return jnp.log1p(x)


class Logarithm(Expression):
    """log(base, expr); NULL when expr <= 0 or base <= 0."""

    def __init__(self, base: Expression, child: Expression):
        super().__init__([base, child])

    @property
    def dtype(self):
        return double

    @property
    def nullable(self):
        return True

    def eval(self, ctx):
        b = self.children[0].eval(ctx)
        c = self.children[1].eval(ctx)
        bd = b.data.astype(jnp.float64)
        cd = c.data.astype(jnp.float64)
        ok = (bd > 0.0) & (cd > 0.0)
        r = jnp.log(jnp.where(cd > 0, cd, 1.0)) / \
            jnp.log(jnp.where(bd > 0, bd, 2.0))
        return DeviceColumn(double, r, binary_validity(b, c) & ok)


class Pow(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self):
        return double

    def eval(self, ctx):
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        r = jnp.power(a.data.astype(jnp.float64),
                      b.data.astype(jnp.float64))
        return DeviceColumn(double, r, binary_validity(a, b))


class Atan2(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self):
        return double

    def eval(self, ctx):
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        r = jnp.arctan2(a.data.astype(jnp.float64),
                        b.data.astype(jnp.float64))
        return DeviceColumn(double, r, binary_validity(a, b))


class Hypot(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self):
        return double

    def eval(self, ctx):
        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        r = jnp.hypot(a.data.astype(jnp.float64),
                      b.data.astype(jnp.float64))
        return DeviceColumn(double, r, binary_validity(a, b))


class Round(Expression):
    """round(x, scale) — HALF_UP (away from zero on ties)."""

    _half_even = False

    def __init__(self, child: Expression, scale: int = 0):
        super().__init__([child])
        self.scale = scale

    @property
    def dtype(self):
        dt = self.children[0].dtype
        if isinstance(dt, (FloatType, DoubleType)):
            return double
        return dt

    def key(self):
        return (type(self).__name__.lower(), self.scale,
                self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        dt = self.children[0].dtype
        s = self.scale
        if isinstance(dt, IntegralType):
            if s >= 0:
                return DeviceColumn(self.dtype, c.data, c.validity)
            f = 10 ** (-s)
            x = c.data.astype(jnp.int64)
            if self._half_even:
                q = jnp.round(x.astype(jnp.float64) / f).astype(jnp.int64)
            else:
                ax = jnp.abs(x)
                q = (ax + f // 2) // f * jnp.sign(x)
            r = (q * f).astype(dt.np_dtype)
            return DeviceColumn(self.dtype, r, c.validity)
        x = c.data.astype(jnp.float64)
        f = 10.0 ** s
        scaled = x * f
        if self._half_even:
            r = jnp.round(scaled)
        else:
            # HALF_UP: ties away from zero. Ties are judged on the binary
            # double value; Spark rounds the decimal string rendering, so
            # values like 1.005 (binary 1.00499...) can differ by 1 ulp of
            # the target scale — the same documented incompat as the
            # reference's GPU round (docs/compatibility.md).
            frac = jnp.abs(scaled - jnp.trunc(scaled))
            r = jnp.where(frac >= 0.5,
                          jnp.trunc(scaled) + jnp.sign(scaled),
                          jnp.trunc(scaled))
        r = r / f
        r = jnp.where(jnp.isfinite(x), r, x)
        return DeviceColumn(double, r, c.validity)


class BRound(Round):
    """bround(x, scale) — HALF_EVEN."""

    _half_even = True


class Ceil(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return long

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        dt = self.children[0].dtype
        if isinstance(dt, IntegralType):
            return DeviceColumn(long, c.data.astype(jnp.int64), c.validity)
        return DeviceColumn(
            long, jnp.ceil(c.data.astype(jnp.float64)).astype(jnp.int64),
            c.validity)


class Floor(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return long

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        dt = self.children[0].dtype
        if isinstance(dt, IntegralType):
            return DeviceColumn(long, c.data.astype(jnp.int64), c.validity)
        return DeviceColumn(
            long, jnp.floor(c.data.astype(jnp.float64)).astype(jnp.int64),
            c.validity)


# --- bitwise ---


class _BitwiseBinary(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def dtype(self):
        return numeric_promotion(self.children[0].dtype,
                                 self.children[1].dtype)

    def _op(self, a, b):
        raise NotImplementedError

    def eval(self, ctx):
        lc = self.children[0].eval(ctx)
        rc = self.children[1].eval(ctx)
        out_t = self.dtype
        a = lc.data.astype(out_t.np_dtype)
        b = rc.data.astype(out_t.np_dtype)
        return DeviceColumn(out_t, self._op(a, b),
                            binary_validity(lc, rc))


class BitwiseAnd(_BitwiseBinary):
    def _op(self, a, b):
        return a & b


class BitwiseOr(_BitwiseBinary):
    def _op(self, a, b):
        return a | b


class BitwiseXor(_BitwiseBinary):
    def _op(self, a, b):
        return a ^ b


class BitwiseNot(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(self.dtype, ~c.data, c.validity)


class _Shift(Expression):
    """Java shift semantics: count masked to the type's bit width."""

    def __init__(self, child: Expression, amount: Expression):
        super().__init__([child, amount])

    @property
    def dtype(self):
        return self.children[0].dtype

    def _mask(self):
        return 63 if isinstance(self.children[0].dtype, LongType) else 31

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        a = self.children[1].eval(ctx)
        cnt = (a.data.astype(jnp.int32) & self._mask()).astype(
            c.data.dtype)
        return DeviceColumn(self.dtype, self._op(c.data, cnt),
                            binary_validity(c, a))


class ShiftLeft(_Shift):
    def _op(self, x, cnt):
        return x << cnt


class ShiftRight(_Shift):
    def _op(self, x, cnt):
        return x >> cnt  # arithmetic on signed ints


class ShiftRightUnsigned(_Shift):
    def _op(self, x, cnt):
        return lax.shift_right_logical(x, cnt)


class Hex(Expression):
    """hex(long) -> uppercase hex string without leading zeros."""

    MAX_NIBBLES = 16

    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return string_t

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        x = c.data.astype(jnp.int64)
        nib_idx = jnp.arange(self.MAX_NIBBLES, dtype=jnp.int64)
        shifts = (self.MAX_NIBBLES - 1 - nib_idx) * 4
        nibbles = lax.shift_right_logical(
            x[:, None], shifts[None, :]) & 0xF
        chars = jnp.where(nibbles < 10, nibbles + ord("0"),
                          nibbles - 10 + ord("A")).astype(jnp.uint8)
        nz = nibbles != 0
        # index of first nonzero nibble (15 when all zero -> "0")
        first = jnp.where(nz.any(axis=1),
                          jnp.argmax(nz, axis=1),
                          self.MAX_NIBBLES - 1).astype(jnp.int32)
        length = (self.MAX_NIBBLES - first).astype(jnp.int32)
        pos = jnp.arange(self.MAX_NIBBLES, dtype=jnp.int32)[None, :]
        src = jnp.clip(first[:, None] + pos, 0, self.MAX_NIBBLES - 1)
        out = jnp.take_along_axis(chars, src.astype(jnp.int64), axis=1)
        keep = pos < length[:, None]
        out = jnp.where(keep, out, 0).astype(jnp.uint8)
        return DeviceColumn(string_t, out, c.validity, length)
