"""Arithmetic expressions with Spark semantics.

Mirrors the coverage of the reference's arithmetic rules
(`sql-plugin/src/main/scala/org/apache/spark/sql/rapids/arithmetic.scala`,
registered from `GpuOverrides.scala:920`): binary type promotion, null
propagation, integral wraparound in non-ANSI mode, divide-by-zero -> null,
Spark's `/` returning double for integral inputs, `div` as integral
divide, and decimal scale arithmetic for the DECIMAL64 range.

ANSI overflow checking runs ON DEVICE: data-dependent raises cannot
happen inside a traced XLA program, so expr/ansicheck.py compiles the
overflow conditions to per-row masks reduced to scalars, and the
operators raise host-side at batch boundaries (the error-flag design
this docstring used to promise).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import EvalContext, Expression, binary_validity
from spark_rapids_tpu.sqltypes import (
    DataType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegralType,
    LongType,
)
from spark_rapids_tpu.sqltypes.datatypes import (
    double,
    long,
    numeric_promotion,
)


class BinaryArithmetic(Expression):
    def __init__(self, left: Expression, right: Expression):
        super().__init__([left, right])

    @property
    def left(self):
        return self.children[0]

    @property
    def right(self):
        return self.children[1]

    def _result_type(self) -> DataType:
        lt, rt = self.left.dtype, self.right.dtype
        if isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            return self._decimal_result_type()
        return numeric_promotion(lt, rt)

    def _decimal_result_type(self) -> DataType:
        lt, rt = self.left.dtype, self.right.dtype
        lp, ls = _dec_prec_scale(lt)
        rp, rs = _dec_prec_scale(rt)
        return self._dec_type(lp, ls, rp, rs)

    @property
    def dtype(self):
        return self._result_type()

    def _promote(self, ctx: EvalContext):
        lt = self.left.eval(ctx)
        rt = self.right.eval(ctx)
        out_t = self._result_type()
        if isinstance(out_t, DecimalType):
            ls = _dec_prec_scale(self.left.dtype)[1]
            rs = _dec_prec_scale(self.right.dtype)[1]
            ld = _to_scaled_i64(lt, ls)
            rd = _to_scaled_i64(rt, rs)
            return ld, rd, lt, rt, out_t, ls, rs
        ld = lt.data.astype(out_t.np_dtype)
        rd = rt.data.astype(out_t.np_dtype)
        return ld, rd, lt, rt, out_t, None, None


def _dec_prec_scale(dt: DataType):
    if isinstance(dt, DecimalType):
        return dt.precision, dt.scale
    if isinstance(dt, IntegralType):
        return 19, 0  # widest integral as decimal(19,0) conceptually
    raise TypeError(f"not decimal-compatible: {dt}")


def _to_scaled_i64(col: DeviceColumn, scale: int) -> jnp.ndarray:
    return col.data.astype(jnp.int64)


def _any_wide(*dts) -> bool:
    from spark_rapids_tpu.ops import decimal128 as d128

    return any(d128.is_wide(dt) for dt in dts)


class Add(BinaryArithmetic):
    _negate_right = False

    def _dec_type(self, lp, ls, rp, rs):
        s = max(ls, rs)
        p = min(DecimalType.MAX_PRECISION, max(lp - ls, rp - rs) + s + 1)
        return DecimalType(p, s)

    def _wide_eval(self, ctx, out_t):
        """DECIMAL128 add/subtract via limb arithmetic
        (ops/decimal128.py; reference spark-rapids-jni DecimalUtils)."""
        from spark_rapids_tpu.ops import decimal128 as d128

        lc = self.left.eval(ctx)
        rc = self.right.eval(ctx)
        s = out_t.scale
        ls = _dec_prec_scale(self.left.dtype)[1]
        rs = _dec_prec_scale(self.right.dtype)[1]
        lh, ll = d128.widen_column(lc, s - ls)
        rh, rl = d128.widen_column(rc, s - rs)
        if self._negate_right:
            rh, rl = d128.neg128(rh, rl)
        hi, lo = d128.add128(lh, ll, rh, rl)
        valid = binary_validity(lc, rc) & d128.fits_precision(
            hi, lo, out_t.precision)
        return DeviceColumn(out_t, d128.join(hi, lo), valid)

    def eval(self, ctx):
        out_t = self._result_type()
        if _any_wide(out_t, self.left.dtype, self.right.dtype):
            return self._wide_eval(ctx, out_t)
        ld, rd, lc, rc, out_t, ls, rs = self._promote(ctx)
        if isinstance(out_t, DecimalType):
            s = out_t.scale
            ld = ld * (10 ** (s - ls))
            rd = rd * (10 ** (s - rs))
        if self._negate_right:
            rd = -rd
        return DeviceColumn(out_t, ld + rd, binary_validity(lc, rc))


class Subtract(Add):
    _negate_right = True


class Multiply(BinaryArithmetic):
    def _dec_type(self, lp, ls, rp, rs):
        s = min(DecimalType.MAX_PRECISION, ls + rs)
        p = min(DecimalType.MAX_PRECISION, lp + rp + 1)
        return DecimalType(p, s)

    def eval(self, ctx):
        out_t = self._result_type()
        if _any_wide(out_t, self.left.dtype, self.right.dtype):
            # only narrow x narrow -> wide has a device lowering; wide
            # OPERANDS are planner-tagged for CPU (typesig check)
            from spark_rapids_tpu.ops import decimal128 as d128

            lc = self.left.eval(ctx)
            rc = self.right.eval(ctx)
            hi, lo = d128.mul_i64_i64(lc.data.astype(jnp.int64),
                                      rc.data.astype(jnp.int64))
            valid = binary_validity(lc, rc) & d128.fits_precision(
                hi, lo, out_t.precision)
            return DeviceColumn(out_t, d128.join(hi, lo), valid)
        ld, rd, lc, rc, out_t, ls, rs = self._promote(ctx)
        return DeviceColumn(out_t, ld * rd, binary_validity(lc, rc))


class Divide(BinaryArithmetic):
    """Spark `/`: always fractional (double for non-decimal inputs);
    divide-by-zero -> null in non-ANSI mode."""

    def _result_type(self):
        lt, rt = self.left.dtype, self.right.dtype
        if isinstance(lt, DecimalType) or isinstance(rt, DecimalType):
            return self._decimal_result_type()
        return double

    def _dec_type(self, lp, ls, rp, rs):
        # Spark: scale = max(6, ls + rp + 1), capped to 64-bit range here.
        s = min(DecimalType.MAX_LONG_DIGITS, max(6, ls + rp + 1))
        return DecimalType(DecimalType.MAX_LONG_DIGITS, s)

    def eval(self, ctx):
        lt = self.left.eval(ctx)
        rt = self.right.eval(ctx)
        out_t = self._result_type()
        if isinstance(out_t, DecimalType):
            ls = _dec_prec_scale(self.left.dtype)[1]
            rs = _dec_prec_scale(self.right.dtype)[1]
            s = out_t.scale
            # (l / r) at scale s: l * 10^(s + rs - ls) / r, rounded half-up.
            num = lt.data.astype(jnp.int64) * (10 ** (s + rs - ls))
            den = rt.data.astype(jnp.int64)
            zero = den == 0
            den_safe = jnp.where(zero, 1, den)
            # truncate toward zero, then round HALF_UP (Spark/BigDecimal).
            qt = jnp.abs(num) // jnp.abs(den_safe)
            rem = jnp.abs(num) - qt * jnp.abs(den_safe)
            qt = qt + (2 * rem >= jnp.abs(den_safe)).astype(jnp.int64)
            signed = jnp.sign(num) * jnp.sign(den_safe) * qt
            valid = binary_validity(lt, rt) & ~zero
            return DeviceColumn(out_t, signed, valid)
        # Spark Divide (non-ANSI): any zero divisor -> null, including
        # doubles (no IEEE Infinity escapes).
        ld = lt.data.astype(jnp.float64)
        rd = rt.data.astype(jnp.float64)
        zero = rd == 0.0
        res = ld / jnp.where(zero, 1.0, rd)
        return DeviceColumn(out_t, res, binary_validity(lt, rt) & ~zero)


class IntegralDivide(BinaryArithmetic):
    """Spark `div`: long result, truncated toward zero, /0 -> null."""

    def _result_type(self):
        return long

    def eval(self, ctx):
        lt = self.left.eval(ctx)
        rt = self.right.eval(ctx)
        ld = lt.data.astype(jnp.int64)
        rd = rt.data.astype(jnp.int64)
        zero = rd == 0
        rd_safe = jnp.where(zero, 1, rd)
        q = jnp.sign(ld) * jnp.sign(rd_safe) * (jnp.abs(ld) // jnp.abs(rd_safe))
        return DeviceColumn(long, q, binary_validity(lt, rt) & ~zero)


class Remainder(BinaryArithmetic):
    """Spark `%`: sign follows dividend (Java semantics), /0 -> null."""

    def eval(self, ctx):
        ld, rd, lc, rc, out_t, _, _ = self._promote(ctx)
        zero = rd == 0
        if isinstance(out_t, (FloatType, DoubleType)):
            rd_safe = jnp.where(zero, jnp.ones((), rd.dtype), rd)
            # Java %: sign follows dividend, truncated quotient.
            r = ld - jnp.trunc(ld / rd_safe) * rd_safe
        else:
            rd_safe = jnp.where(zero, jnp.ones((), rd.dtype), rd)
            r = ld - (jnp.sign(ld) * jnp.sign(rd_safe) *
                      (jnp.abs(ld) // jnp.abs(rd_safe))) * rd_safe
        return DeviceColumn(out_t, r, binary_validity(lc, rc) & ~zero)


class Pmod(BinaryArithmetic):
    """Positive modulus."""

    def eval(self, ctx):
        ld, rd, lc, rc, out_t, _, _ = self._promote(ctx)
        zero = rd == 0
        rd_safe = jnp.where(zero, jnp.ones((), rd.dtype), rd)
        r = ld % rd_safe
        r = jnp.where(r < 0, r + jnp.abs(rd_safe), r)
        return DeviceColumn(out_t, r, binary_validity(lc, rc) & ~zero)


class UnaryMinus(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if c.data.ndim == 2 and isinstance(c.dtype, DecimalType):
            from spark_rapids_tpu.ops import decimal128 as d128

            hi, lo = d128.neg128(*d128.split(c.data))
            return DeviceColumn(self.dtype, d128.join(hi, lo),
                                c.validity)
        return DeviceColumn(self.dtype, -c.data, c.validity, c.lengths)


class Abs(Expression):
    def __init__(self, child: Expression):
        super().__init__([child])

    @property
    def dtype(self):
        return self.children[0].dtype

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if c.data.ndim == 2 and isinstance(c.dtype, DecimalType):
            from spark_rapids_tpu.ops import decimal128 as d128

            hi, lo, _ = d128.abs128(*d128.split(c.data))
            return DeviceColumn(self.dtype, d128.join(hi, lo),
                                c.validity)
        return DeviceColumn(self.dtype, jnp.abs(c.data), c.validity,
                            c.lengths)
