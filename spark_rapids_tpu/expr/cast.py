"""Cast — the Spark-compatible conversion matrix (GpuCast.scala analog).

v1 device coverage (non-ANSI semantics):
- numeric <-> numeric: integral narrowing wraps (Java semantics);
  float -> integral saturates then truncates toward zero, NaN -> 0
  (Java (long)(double) semantics); integral -> float is widening.
- boolean <-> numeric.
- date -> timestamp (midnight UTC) and timestamp -> date (floor days).
- numeric/boolean/date -> string: digit-by-digit device formatting.
- decimal <-> integral/decimal rescaling.
- string -> int/long/double/date: NOT on device in v1; the planner tags
  Cast(string -> x) for CPU fallback (the reference spent `CastStrings`
  JNI kernels + 1,900 Scala lines here; a pallas parser is future work).

Cast never raises in non-ANSI mode; invalid casts produce null. Under
spark.sql.ansi.enabled, numeric narrowing and float->integral casts
raise ON DEVICE via the compiled overflow-mask check
(expr/ansicheck.py); string/decimal ANSI casts keep the CPU path where
errors raise eagerly.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import (
    BooleanType,
    DataType,
    DateType,
    DecimalType,
    DoubleType,
    FloatType,
    IntegralType,
    LongType,
    StringType,
    TimestampType,
)

_US_PER_DAY = 86_400_000_000


class Cast(Expression):
    """tz: session timezone stamped at resolution time — governs
    timestamp<->date/string conversions (GpuCast + GpuTimeZoneDB role)
    and participates in the jit key."""

    tz: str = "UTC"

    def __init__(self, child: Expression, to: DataType):
        super().__init__([child])
        self.to = to

    @property
    def dtype(self):
        return self.to

    def key(self):
        return ("cast", repr(self.to), self.tz, self.children[0].key())

    def device_supported(self) -> bool:
        from spark_rapids_tpu.ops import decimal128 as d128

        frm = self.children[0].dtype
        if d128.is_wide(self.to) and isinstance(
                frm, (FloatType, DoubleType, StringType)):
            return False  # needs exact big-int parse/scale: CPU
        if (isinstance(self.to, StringType) and d128.is_wide(frm)
                and frm.scale > 18):
            return False  # fraction chunk exceeds one 64-bit divisor
        return True

    def can_fail(self) -> bool:
        """True when this cast can produce an error in ANSI mode
        (invalid parse, overflow). The planner keeps ANSI-mode failable
        casts on the CPU path, where errors raise eagerly
        (spark.sql.ansi.enabled handling; GpuCast ansi kernels are the
        device-side follow-up)."""
        frm = self.children[0].dtype
        to = self.to
        if isinstance(frm, StringType) and not isinstance(to, StringType):
            return True
        if isinstance(frm, (FloatType, DoubleType)) and isinstance(
                to, IntegralType):
            return True
        if isinstance(frm, IntegralType) and isinstance(to, IntegralType):
            return _int_width(to) < _int_width(frm)
        if isinstance(to, DecimalType):
            return True
        return False

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        frm, to = c.dtype, self.to
        if frm == to:
            return c
        if isinstance(frm, StringType):
            return _cast_from_string(c, to, self.tz)
        if isinstance(to, StringType):
            return _cast_to_string(c, self.tz)
        if isinstance(frm, BooleanType):
            data = c.data.astype(to.np_dtype)
            return DeviceColumn(to, data, c.validity)
        if isinstance(to, BooleanType):
            return DeviceColumn(to, c.data != 0, c.validity)
        if isinstance(frm, DateType) and isinstance(to, TimestampType):
            # local midnight in the session zone -> UTC instant
            local = c.data.astype(jnp.int64) * _US_PER_DAY
            if not _is_utc(self.tz):
                from spark_rapids_tpu.ops import tzdb

                local = tzdb.local_to_utc(local, self.tz)
            return DeviceColumn(to, local, c.validity)
        if isinstance(frm, TimestampType) and isinstance(to, DateType):
            us = c.data
            if not _is_utc(self.tz):
                from spark_rapids_tpu.ops import tzdb

                us = tzdb.utc_to_local(us, self.tz)
            d = jnp.floor_divide(us, _US_PER_DAY).astype(jnp.int32)
            return DeviceColumn(to, d, c.validity)
        if isinstance(frm, DecimalType) or isinstance(to, DecimalType):
            return _cast_decimal(c, frm, to)
        if isinstance(frm, (FloatType, DoubleType)) and isinstance(
                to, IntegralType):
            # Java (int)/(long) of float: truncate toward zero, saturate,
            # NaN -> 0.
            f = c.data.astype(jnp.float64)
            info = jnp.iinfo(to.np_dtype)
            t = jnp.trunc(f)
            t = jnp.clip(t, float(info.min), float(info.max))
            t = jnp.where(jnp.isnan(f), 0.0, t)
            return DeviceColumn(to, t.astype(to.np_dtype), c.validity)
        # numeric widening/narrowing (wraps like Java) and int->float
        return DeviceColumn(to, c.data.astype(to.np_dtype), c.validity)


def _int_width(dt: DataType) -> int:
    import numpy as np

    return np.dtype(dt.np_dtype).itemsize


def _is_utc(tz: str) -> bool:
    from spark_rapids_tpu.ops import tzdb

    return tzdb.is_utc(tz)


def _cast_from_string(c: DeviceColumn, to: DataType,
                      tz: str = "UTC") -> DeviceColumn:
    """Device string parsing (ops/stringcast.py; the CastStrings JNI
    kernel role). Invalid input -> null (non-ANSI)."""
    from spark_rapids_tpu.ops import stringcast as SC

    if isinstance(to, BooleanType):
        return SC.parse_bool(c, to)
    if isinstance(to, IntegralType):
        return SC.parse_long(c, to)
    if isinstance(to, (FloatType, DoubleType)):
        return SC.parse_double(c, to)
    if isinstance(to, DecimalType):
        return SC.parse_decimal(c, to)
    if isinstance(to, DateType):
        return SC.parse_date(c, to)
    if isinstance(to, TimestampType):
        out = SC.parse_timestamp(c, to)
        if not _is_utc(tz):
            # the parsed wall-clock is in the session zone
            from spark_rapids_tpu.ops import tzdb

            out = DeviceColumn(out.dtype,
                               tzdb.local_to_utc(out.data, tz),
                               out.validity)
        return out
    raise TypeError(f"cast string -> {to} not supported on device")


def _cast_decimal(c: DeviceColumn, frm: DataType, to: DataType
                  ) -> DeviceColumn:
    from spark_rapids_tpu.ops import decimal128 as d128

    fs = frm.scale if isinstance(frm, DecimalType) else 0
    frm_wide = c.data.ndim == 2
    to_wide = d128.is_wide(to) if isinstance(to, DecimalType) else False
    if frm_wide or to_wide:
        return _cast_decimal_wide(c, frm, to, fs, frm_wide, to_wide)
    if isinstance(to, DecimalType):
        ts = to.scale
        if isinstance(frm, (FloatType, DoubleType)):
            # HALF_UP (Spark BigDecimal), not jnp.round's half-to-even
            x = c.data.astype(jnp.float64) * (10.0 ** ts)
            scaled = jnp.sign(x) * jnp.floor(jnp.abs(x) + 0.5)
            data = scaled.astype(jnp.int64)
            # overflow of the target precision -> null (non-ANSI)
            limit = 10 ** min(18, to.precision)
            valid = c.validity & (jnp.abs(scaled) < float(limit))
            return DeviceColumn(to, data, valid)
        src = c.data.astype(jnp.int64)
        if ts >= fs:
            data = src * (10 ** (ts - fs))
        else:
            f = 10 ** (fs - ts)
            q = jnp.abs(src) // f
            rem = jnp.abs(src) - q * f
            q = q + (2 * rem >= f).astype(jnp.int64)  # HALF_UP
            data = jnp.sign(src) * q
        limit = 10 ** min(18, to.precision)
        valid = c.validity & (jnp.abs(data) < limit)
        return DeviceColumn(to, data, valid)
    # decimal -> numeric
    if isinstance(to, (FloatType, DoubleType)):
        data = c.data.astype(jnp.float64) / (10.0 ** fs)
        return DeviceColumn(to, data.astype(to.np_dtype), c.validity)
    f = 10 ** fs
    q = jnp.sign(c.data) * (jnp.abs(c.data.astype(jnp.int64)) // f)
    return DeviceColumn(to, q.astype(to.np_dtype), c.validity)


def _cast_decimal_wide(c: DeviceColumn, frm: DataType, to: DataType,
                       fs: int, frm_wide: bool, to_wide: bool
                       ) -> DeviceColumn:
    """DECIMAL128 conversions via limb arithmetic (ops/decimal128.py;
    the DecimalUtils role). float->wide and string parsing are planner-
    tagged for CPU (typesig)."""
    from spark_rapids_tpu.ops import decimal128 as d128

    if isinstance(to, DecimalType):
        if isinstance(frm, (FloatType, DoubleType)):
            raise TypeError(
                "float -> decimal128 has no device lowering (CPU)")
        hi, lo = d128.widen_column(c, to.scale - fs)
        valid = c.validity & d128.fits_precision(hi, lo, to.precision)
        if to_wide:
            return DeviceColumn(to, d128.join(hi, lo), valid)
        valid = valid & d128.fits_i64(hi, lo)
        return DeviceColumn(to, lo, valid)
    # wide decimal -> numeric
    hi, lo = d128.split(c.data)
    if isinstance(to, (FloatType, DoubleType)):
        data = d128.to_f64(hi, lo) / (10.0 ** fs)
        return DeviceColumn(to, data.astype(to.np_dtype), c.validity)
    # integral: truncate the fraction (Spark cast), then wrap like Java
    if fs:
        ah, al, neg = d128.abs128(hi, lo)
        qh, ql, _ = d128.divmod_u128_u64(ah, al, 10 ** min(fs, 18))
        if fs > 18:
            qh, ql, _ = d128.divmod_u128_u64(qh, ql, 10 ** (fs - 18))
        nh, nl = d128.neg128(qh, ql)
        hi = jnp.where(neg, nh, qh)
        lo = jnp.where(neg, nl, ql)
    return DeviceColumn(to, lo.astype(to.np_dtype), c.validity)


_MAX_DIGITS = 20


def _cast_to_string(c: DeviceColumn, tz: str = "UTC") -> DeviceColumn:
    """Integral/boolean/date/timestamp -> UTF-8 padded byte matrix,
    fully on device."""
    from spark_rapids_tpu.sqltypes.datatypes import string as string_t

    if isinstance(c.dtype, TimestampType):
        return _timestamp_to_string(c, tz)
    if isinstance(c.dtype, BooleanType):
        mb = 8
        tmat = jnp.zeros((2, mb), jnp.uint8)
        tmat = tmat.at[0, :5].set(jnp.asarray(list(b"false"), jnp.uint8))
        tmat = tmat.at[1, :4].set(jnp.asarray(list(b"true"), jnp.uint8))
        idx = c.data.astype(jnp.int32)
        data = tmat[idx]
        lengths = jnp.where(c.data, 4, 5).astype(jnp.int32)
        return DeviceColumn(string_t, data, c.validity, lengths)
    if isinstance(c.dtype, DateType):
        return _date_to_string(c)
    if isinstance(c.dtype, IntegralType):
        return _int_to_string(c.data.astype(jnp.int64), c.validity)
    if isinstance(c.dtype, DecimalType):
        return _decimal_to_string(c)
    raise TypeError(f"cast {c.dtype} -> string not supported on device")


def _int_to_string(v: jnp.ndarray, validity: jnp.ndarray) -> DeviceColumn:
    from spark_rapids_tpu.sqltypes.datatypes import string as string_t

    n = v.shape[0]
    neg = v < 0
    # abs(INT64_MIN) overflows; handle via unsigned-style digit loop on
    # negated positive magnitudes digit by digit.
    mag = jnp.where(neg, -(v + 1), v)  # mag = |v| - 1 for negatives
    digits = []
    rest = mag
    adj = neg.astype(jnp.int64)  # add back the 1 in the last digit
    # produce digits least-significant first over |v| = mag + adj
    carry = adj
    for _ in range(_MAX_DIGITS):
        d = rest % 10 + carry
        carry = (d >= 10).astype(jnp.int64)
        d = d % 10
        digits.append(d)
        rest = rest // 10
    digs = jnp.stack(digits, axis=1)  # [n, MAX] LSB first
    # significant digit count (>=1 so "0" renders)
    nd = jnp.ones((n,), jnp.int32)
    for i in range(1, _MAX_DIGITS):
        nd = jnp.where(digs[:, i] > 0, i + 1, nd)
    total_len = nd + neg.astype(jnp.int32)
    mb = 32
    pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
    # char at position p: '-' if p==0 and neg; else digit index
    # (total_len-1-p) from LSB-first array
    digit_idx = (total_len[:, None] - 1 - pos)
    digit_idx_safe = jnp.clip(digit_idx, 0, _MAX_DIGITS - 1)
    dchar = jnp.take_along_axis(digs, digit_idx_safe.astype(jnp.int64),
                                axis=1) + ord("0")
    out = jnp.where(neg[:, None] & (pos == 0), ord("-"), dchar)
    mask = pos < total_len[:, None]
    out = jnp.where(mask, out, 0).astype(jnp.uint8)
    return DeviceColumn(string_t, out, validity, total_len)


def _date_to_string(c: DeviceColumn) -> DeviceColumn:
    """days since epoch -> 'YYYY-MM-DD' on device."""
    from spark_rapids_tpu.expr.datetimes import civil_from_days
    from spark_rapids_tpu.sqltypes.datatypes import string as string_t

    y, m, d = civil_from_days(c.data.astype(jnp.int64))
    mb = 16
    n = c.data.shape[0]

    def digit(x, p):
        return (x // (10 ** p)) % 10 + ord("0")

    cols = [
        digit(y, 3), digit(y, 2), digit(y, 1), digit(y, 0),
        jnp.full((n,), ord("-")),
        digit(m, 1), digit(m, 0),
        jnp.full((n,), ord("-")),
        digit(d, 1), digit(d, 0),
    ]
    out = jnp.zeros((n, mb), jnp.uint8)
    for i, col in enumerate(cols):
        out = out.at[:, i].set(col.astype(jnp.uint8))
    lengths = jnp.full((n,), 10, jnp.int32)
    return DeviceColumn(string_t, out, c.validity, lengths)


def _timestamp_to_string(c: DeviceColumn, tz: str = "UTC") -> DeviceColumn:
    """epoch-us -> 'YYYY-MM-DD HH:MM:SS[.ffffff]' in the session zone,
    trailing fraction zeros trimmed (Spark cast-to-string format;
    GpuCast.scala castTimestampToString)."""
    from spark_rapids_tpu.expr.datetimes import civil_from_days
    from spark_rapids_tpu.sqltypes.datatypes import string as string_t

    us = c.data
    if not _is_utc(tz):
        from spark_rapids_tpu.ops import tzdb

        us = tzdb.utc_to_local(us, tz)
    days = jnp.floor_divide(us, 86_400_000_000)
    in_day = us - days * 86_400_000_000
    y, m, d = civil_from_days(days)
    hh = in_day // 3_600_000_000
    mi = (in_day // 60_000_000) % 60
    ss = (in_day // 1_000_000) % 60
    frac = in_day % 1_000_000

    def digit(x, p):
        return ((x // (10 ** p)) % 10 + ord("0")).astype(jnp.uint8)

    n = c.data.shape[0]
    mb = 32
    out = jnp.zeros((n, mb), jnp.uint8)
    fixed = [
        digit(y, 3), digit(y, 2), digit(y, 1), digit(y, 0),
        jnp.full((n,), ord("-"), jnp.uint8),
        digit(m, 1), digit(m, 0),
        jnp.full((n,), ord("-"), jnp.uint8),
        digit(d, 1), digit(d, 0),
        jnp.full((n,), ord(" "), jnp.uint8),
        digit(hh, 1), digit(hh, 0),
        jnp.full((n,), ord(":"), jnp.uint8),
        digit(mi, 1), digit(mi, 0),
        jnp.full((n,), ord(":"), jnp.uint8),
        digit(ss, 1), digit(ss, 0),
    ]
    for i, col in enumerate(fixed):
        out = out.at[:, i].set(col)
    # fraction: 6 digits with trailing zeros trimmed; none when frac==0
    trailing = jnp.zeros((n,), jnp.int32)
    for z in range(1, 7):
        trailing = jnp.where(frac % (10 ** z) == 0, z, trailing)
    has_frac = frac > 0
    ndig = jnp.where(has_frac, 6 - trailing, 0)
    out = out.at[:, 19].set(jnp.where(has_frac, ord("."), 0
                                      ).astype(jnp.uint8))
    for j in range(6):
        dj = digit(frac, 5 - j)
        keep = j < ndig
        out = out.at[:, 20 + j].set(jnp.where(keep, dj, 0
                                              ).astype(jnp.uint8))
    lengths = (19 + jnp.where(has_frac, ndig + 1, 0)).astype(jnp.int32)
    return DeviceColumn(string_t, out, c.validity, lengths)


def _decimal_to_string(c: DeviceColumn) -> DeviceColumn:
    from spark_rapids_tpu.sqltypes.datatypes import string as string_t

    if c.data.ndim == 2:  # DECIMAL128 limb matrix
        from spark_rapids_tpu.ops import decimal128 as d128

        mat, lengths = d128.decimal_string(*d128.split(c.data),
                                           c.dtype.scale)
        return DeviceColumn(string_t, mat, c.validity, lengths)

    s = c.dtype.scale
    if s == 0:
        return _int_to_string(c.data.astype(jnp.int64), c.validity)
    f = 10 ** s
    whole = jnp.sign(c.data) * (jnp.abs(c.data.astype(jnp.int64)) // f)
    frac = jnp.abs(c.data.astype(jnp.int64)) % f
    w = _int_to_string(whole, c.validity)
    neg_zero = (whole == 0) & (c.data < 0)
    n = c.data.shape[0]
    mb = 48
    out = jnp.zeros((n, mb), jnp.uint8)
    # shift whole part right by 1 where we need a '-' for -0.xx
    wlen = w.lengths + neg_zero.astype(jnp.int32)
    pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
    wsrc = jnp.clip(pos - neg_zero[:, None].astype(jnp.int32), 0,
                    w.max_bytes - 1)
    body = jnp.take_along_axis(
        jnp.pad(w.data, ((0, 0), (0, mb - w.max_bytes))),
        wsrc.astype(jnp.int64), axis=1)
    body = jnp.where(neg_zero[:, None] & (pos == 0), ord("-"), body)
    out = jnp.where(pos < wlen[:, None], body, 0)
    # '.' then fraction digits (fixed s digits)
    out = jnp.where(pos == wlen[:, None], ord("."), out)
    fpos = pos - wlen[:, None] - 1
    fdig = (frac[:, None] //
            (10 ** jnp.clip(s - 1 - fpos, 0, 18))) % 10 + ord("0")
    in_frac = (fpos >= 0) & (fpos < s)
    out = jnp.where(in_frac, fdig, out).astype(jnp.uint8)
    lengths = (wlen + 1 + s).astype(jnp.int32)
    return DeviceColumn(string_t, out, c.validity, lengths)
