"""Conditional expressions: If, CaseWhen, Coalesce.

Reference coverage: `conditionalExpressions.scala` rules registered in
`GpuOverrides.scala`. All branches evaluate unconditionally (XLA selects
between them) — the same "evaluate both sides then select" model the
device plan uses on cuDF, and exactly what a vector machine wants.
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression
from spark_rapids_tpu.sqltypes import StringType


def _select(pred: jnp.ndarray, a: DeviceColumn, b: DeviceColumn
            ) -> DeviceColumn:
    """Row-wise select; operands must share dtype (and trailing widths
    — pad first via _common_width)."""
    from spark_rapids_tpu.columnar.batch import row_select

    def sel(x, y):
        return row_select(pred, x, y)

    data = sel(a.data, b.data)
    validity = jnp.where(pred, a.validity, b.validity)
    lengths = None
    if a.lengths is not None:
        lengths = jnp.where(pred, a.lengths, b.lengths)
    ev = (None if a.elem_validity is None
          else sel(a.elem_validity, b.elem_validity))
    el = (None if a.elem_lengths is None
          else sel(a.elem_lengths, b.elem_lengths))
    mv = None if a.map_values is None else sel(a.map_values,
                                               b.map_values)
    return DeviceColumn(a.dtype, data, validity, lengths, ev, mv,
                        elem_lengths=el)


def _common_width(cols):
    """Pad variable-width columns (strings, arrays, array<string>
    cubes) to common trailing dims so _select's wheres line up."""
    from spark_rapids_tpu.columnar.batch import pad_trailing

    nd = max(c.data.ndim for c in cols)
    if nd == 1:
        return cols
    target = tuple(
        max(int(c.data.shape[ax]) if c.data.ndim > ax else 1
            for c in cols)
        for ax in range(1, nd))
    out = []
    for c in cols:
        if c.data.ndim == 1 or tuple(c.data.shape[1:]) == target:
            out.append(c)
            continue
        out.append(c.replace(
            data=pad_trailing(c.data, target),
            elem_validity=pad_trailing(c.elem_validity, target[:1]),
            elem_lengths=pad_trailing(c.elem_lengths, target[:1]),
            map_values=pad_trailing(c.map_values, target[:1])))
    return out


class If(Expression):
    def __init__(self, pred: Expression, then: Expression, els: Expression):
        super().__init__([pred, then, els])

    @property
    def dtype(self):
        return self.children[1].dtype

    def eval(self, ctx):
        p = self.children[0].eval(ctx)
        t = self.children[1].eval(ctx)
        e = self.children[2].eval(ctx)
        t, e = _common_width([t, e])
        cond = p.data & p.validity  # null predicate -> else branch
        return _select(cond, t, e)


class CaseWhen(Expression):
    """CASE WHEN c1 THEN v1 ... ELSE e END."""

    def __init__(self, branches, else_expr=None):
        children = []
        for c, v in branches:
            children.extend([c, v])
        self.n_branches = len(branches)
        self.has_else = else_expr is not None
        if else_expr is not None:
            children.append(else_expr)
        super().__init__(children)

    @property
    def dtype(self):
        return self.children[1].dtype

    @property
    def nullable(self):
        if not self.has_else:
            return True
        return any(c.nullable for c in self.children)

    def key(self):
        return ("case", self.n_branches, self.has_else,
                tuple(c.key() for c in self.children))

    def eval(self, ctx):
        vals = []
        conds = []
        for i in range(self.n_branches):
            c = self.children[2 * i].eval(ctx)
            v = self.children[2 * i + 1].eval(ctx)
            conds.append(c.data & c.validity)
            vals.append(v)
        if self.has_else:
            els = self.children[-1].eval(ctx)
        else:
            # all-null column with EVERY leaf of the branch layout
            # zeroed (validity zeros == all null) — leaf-complete for
            # strings/arrays/cubes without per-field plumbing
            import jax

            els = jax.tree_util.tree_map(jnp.zeros_like, vals[0])
        cols = _common_width(vals + [els])
        vals, out = cols[:-1], cols[-1]
        taken = jnp.zeros(conds[0].shape, bool)
        # first matching branch wins
        for cond, v in zip(conds, vals):
            fire = cond & ~taken
            out = _select(fire, v, out)
            taken = taken | cond
        return out


class Coalesce(Expression):
    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def dtype(self):
        return self.children[0].dtype

    @property
    def nullable(self):
        return all(c.nullable for c in self.children)

    def eval(self, ctx):
        cols = _common_width([c.eval(ctx) for c in self.children])
        out = cols[0]
        for c in cols[1:]:
            out = _select(out.validity, out, c)
        return out


class Greatest(Expression):
    """greatest(...): max skipping nulls; NaN is greatest (Spark
    ordering); null only when all inputs are null."""

    _is_greatest = True

    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import numeric_promotion

        t = self.children[0].dtype
        for c in self.children[1:]:
            t = numeric_promotion(t, c.dtype)
        return t

    def eval(self, ctx):
        import jax.numpy as jnp

        from spark_rapids_tpu.sqltypes import DoubleType, FloatType

        out_t = self.dtype
        cols = [c.eval(ctx) for c in self.children]
        is_float = isinstance(out_t, (FloatType, DoubleType))
        datas = [c.data.astype(out_t.np_dtype) for c in cols]
        valids = [c.validity for c in cols]
        any_valid = valids[0]
        for v in valids[1:]:
            any_valid = any_valid | v
        if is_float:
            # Spark orders NaN greatest: greatest() is NaN iff ANY valid
            # input is NaN; least() is NaN iff ALL valid inputs are NaN.
            inf = jnp.asarray(jnp.inf, out_t.np_dtype)
            neutral = -inf if self._is_greatest else inf
            acc = jnp.full(datas[0].shape, neutral, out_t.np_dtype)
            any_nan = jnp.zeros(datas[0].shape, bool)
            all_nan = jnp.ones(datas[0].shape, bool)
            for d, v in zip(datas, valids):
                isnan = jnp.isnan(d) & v
                any_nan = any_nan | isnan
                all_nan = all_nan & (~v | jnp.isnan(d))
                key = jnp.where(v & ~isnan, d, neutral)
                acc = jnp.maximum(acc, key) if self._is_greatest \
                    else jnp.minimum(acc, key)
            nan_wins = any_nan if self._is_greatest \
                else (all_nan & any_valid)
            acc = jnp.where(nan_wins, jnp.asarray(jnp.nan, out_t.np_dtype),
                            acc)
        else:
            lo = jnp.iinfo(out_t.np_dtype).min
            hi = jnp.iinfo(out_t.np_dtype).max
            neutral = lo if self._is_greatest else hi
            acc = jnp.full(datas[0].shape, neutral, out_t.np_dtype)
            for d, v in zip(datas, valids):
                key = jnp.where(v, d, neutral)
                acc = jnp.maximum(acc, key) if self._is_greatest \
                    else jnp.minimum(acc, key)
        from spark_rapids_tpu.columnar.batch import DeviceColumn

        return DeviceColumn(out_t, acc, any_valid)


class Least(Greatest):
    _is_greatest = False


class Nvl2(Expression):
    """nvl2(a, b, c): b when a is not null else c."""

    def __init__(self, a, b, c):
        super().__init__([a, b, c])

    @property
    def dtype(self):
        return self.children[1].dtype

    def eval(self, ctx):
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.batch import DeviceColumn

        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        c = self.children[2].eval(ctx)
        cond = a.validity
        if b.lengths is not None:
            mb = max(b.max_bytes, c.max_bytes)
            bd = jnp.pad(b.data, ((0, 0), (0, mb - b.max_bytes)))
            cd = jnp.pad(c.data, ((0, 0), (0, mb - c.max_bytes)))
            data = jnp.where(cond[:, None], bd, cd)
            lens = jnp.where(cond, b.lengths, c.lengths)
            return DeviceColumn(self.dtype, data,
                                jnp.where(cond, b.validity, c.validity),
                                lens)
        data = jnp.where(cond, b.data, c.data)
        return DeviceColumn(self.dtype, data,
                            jnp.where(cond, b.validity, c.validity))


class NaNvl(Expression):
    """nanvl(a, b): b when a is NaN else a (doubles)."""

    def __init__(self, a, b):
        super().__init__([a, b])

    @property
    def dtype(self):
        from spark_rapids_tpu.sqltypes.datatypes import double

        return double

    def eval(self, ctx):
        import jax.numpy as jnp

        from spark_rapids_tpu.columnar.batch import DeviceColumn

        a = self.children[0].eval(ctx)
        b = self.children[1].eval(ctx)
        ad = a.data.astype(jnp.float64)
        bd = b.data.astype(jnp.float64)
        isnan = jnp.isnan(ad) & a.validity
        return DeviceColumn(self.dtype, jnp.where(isnan, bd, ad),
                            jnp.where(isnan, b.validity, a.validity))
