from spark_rapids_tpu.expr.core import (  # noqa: F401
    Expression,
    BoundReference,
    Literal,
    EvalContext,
    Alias,
)
from spark_rapids_tpu.expr.arith import (  # noqa: F401
    Add, Subtract, Multiply, Divide, IntegralDivide, Remainder, Pmod,
    UnaryMinus, Abs,
)
from spark_rapids_tpu.expr.mathexpr import (  # noqa: F401
    Acos, Acosh, Asin, Asinh, Atan, Atan2, Atanh, BitwiseAnd, BitwiseNot,
    BitwiseOr, BitwiseXor, BRound, Cbrt, Ceil, Cos, Cosh, Cot, Exp, Expm1,
    Floor, Hex, Hypot, Log, Log10, Log1p, Log2, Logarithm, Pow, Rint,
    Round, ShiftLeft, ShiftRight, ShiftRightUnsigned, Signum, Sin, Sinh,
    Sqrt, Tan, Tanh, ToDegrees, ToRadians,
)
from spark_rapids_tpu.expr.predicates import (  # noqa: F401
    EqualTo, EqualNullSafe, LessThan, LessThanOrEqual, GreaterThan,
    GreaterThanOrEqual, And, Or, Not, IsNull, IsNotNull, IsNaN, In,
)
from spark_rapids_tpu.expr.conditional import (  # noqa: F401
    If, CaseWhen, Coalesce, Greatest, Least, NaNvl, Nvl2,
)
from spark_rapids_tpu.expr.cast import Cast  # noqa: F401
from spark_rapids_tpu.expr.strings import (  # noqa: F401
    Ascii, Chr, Concat, ConcatWs, Contains, EndsWith, InitCap, Length,
    Lower, StartsWith, StringInstr, StringLocate, StringLPad, StringRepeat,
    StringReplace, StringReverse, StringRPad, StringTranslate, StringTrim,
    StringTrimLeft, StringTrimRight, Substring, SubstringIndex, Upper,
)
from spark_rapids_tpu.expr.datetimes import (  # noqa: F401
    Year, Month, DayOfMonth, Hour, Minute, Second,
)
from spark_rapids_tpu.expr.aggregates import (  # noqa: F401
    AggregateFunction, Sum, Count, Min, Max, Average, First, Last,
)
from spark_rapids_tpu.expr.hashexpr import Murmur3Hash, XxHash64  # noqa: F401
from spark_rapids_tpu.expr.windows import (  # noqa: F401
    CumeDist, DenseRank, Lag, Lead, NTile, PercentRank, Rank, RowNumber,
    WindowExpression, WindowFrame, WindowSpecDef,
)
from spark_rapids_tpu.expr.regexexpr import (  # noqa: F401
    RegexpExtract, RegexpReplace, RLike,
)
from spark_rapids_tpu.expr.collections import (  # noqa: F401
    ArrayContains,
    ArrayFilter,
    ArrayMax,
    ArrayMin,
    ArrayTransform,
    CreateArray,
    ElementAt,
    GetArrayItem,
    Size,
    SortArray,
)
from spark_rapids_tpu.expr.jsonexpr import (  # noqa: F401
    GetJsonObject,
    ParseUrl,
)
from spark_rapids_tpu.expr.deviceudf import DeviceUDF  # noqa: F401
from spark_rapids_tpu.expr.structs import (  # noqa: F401
    CreateNamedStruct,
    GetStructField,
)
from spark_rapids_tpu.expr.generators import Explode, PosExplode  # noqa: F401
