from spark_rapids_tpu.expr.core import (  # noqa: F401
    Expression,
    BoundReference,
    Literal,
    EvalContext,
    Alias,
)
from spark_rapids_tpu.expr.arith import (  # noqa: F401
    Add, Subtract, Multiply, Divide, IntegralDivide, Remainder, Pmod,
    UnaryMinus, Abs,
)
from spark_rapids_tpu.expr.predicates import (  # noqa: F401
    EqualTo, EqualNullSafe, LessThan, LessThanOrEqual, GreaterThan,
    GreaterThanOrEqual, And, Or, Not, IsNull, IsNotNull, IsNaN, In,
)
from spark_rapids_tpu.expr.conditional import (  # noqa: F401
    If, CaseWhen, Coalesce,
)
from spark_rapids_tpu.expr.cast import Cast  # noqa: F401
from spark_rapids_tpu.expr.strings import (  # noqa: F401
    Length, Upper, Lower, Substring, Concat, StartsWith, EndsWith, Contains,
)
from spark_rapids_tpu.expr.datetimes import (  # noqa: F401
    Year, Month, DayOfMonth, Hour, Minute, Second,
)
from spark_rapids_tpu.expr.aggregates import (  # noqa: F401
    AggregateFunction, Sum, Count, Min, Max, Average, First,
)
from spark_rapids_tpu.expr.hashexpr import Murmur3Hash  # noqa: F401
from spark_rapids_tpu.expr.windows import (  # noqa: F401
    CumeDist, DenseRank, Lag, Lead, NTile, PercentRank, Rank, RowNumber,
    WindowExpression, WindowFrame, WindowSpecDef,
)
