"""Window expressions — the GpuWindowExpression analog
(reference: GpuWindowExpression.scala, GpuWindowExecMeta.scala:673;
function registry GpuOverrides.scala window expr rules).

A `WindowExpression` pairs a window function (ranking function, lead/lag,
or any AggregateFunction) with a `WindowSpecDef` (partition exprs, sort
orders, frame). Evaluation happens inside `TpuWindowExec`, which traces
the whole spec — sort, frame bounds, every function — into one XLA
program; expression nodes here only carry structure and types.
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

from spark_rapids_tpu.expr.aggregates import AggregateFunction
from spark_rapids_tpu.expr.core import Expression, Literal
from spark_rapids_tpu.plan.logical import SortOrder
from spark_rapids_tpu.sqltypes import DataType
from spark_rapids_tpu.sqltypes.datatypes import double, integer


class WindowFrame:
    """ROWS/RANGE frame; lower/upper: None = UNBOUNDED, 0 = CURRENT ROW,
    other values = offsets (negative = PRECEDING)."""

    def __init__(self, frame_type: str, lower, upper):
        assert frame_type in ("rows", "range")
        self.frame_type = frame_type
        self.lower = lower
        self.upper = upper

    def key(self) -> Tuple:
        return (self.frame_type, self.lower, self.upper)

    def __repr__(self):
        def b(v, side):
            if v is None:
                return f"unbounded {side}"
            if v == 0:
                return "current row"
            return f"{abs(v)} {'preceding' if v < 0 else 'following'}"
        return (f"{self.frame_type} between {b(self.lower, 'preceding')} "
                f"and {b(self.upper, 'following')}")


class WindowSpecDef:
    def __init__(self, partitions: Sequence[Expression],
                 orders: Sequence[SortOrder],
                 frame: Optional[WindowFrame] = None):
        self.partitions = list(partitions)
        self.orders = list(orders)
        self.frame = frame

    def sort_key(self) -> Tuple:
        """Groups window expressions that can share one sorted pass."""
        return (tuple(p.key() for p in self.partitions),
                tuple((o.expr.key(), o.ascending, o.nulls_first)
                      for o in self.orders))

    def key(self) -> Tuple:
        return self.sort_key() + (
            self.frame.key() if self.frame else None,)


class WindowFunction(Expression):
    """Ranking/offset functions valid only inside a window spec."""

    needs_order = True

    @property
    def nullable(self):
        return False


class RowNumber(WindowFunction):
    @property
    def dtype(self) -> DataType:
        return integer


class Rank(WindowFunction):
    @property
    def dtype(self) -> DataType:
        return integer


class DenseRank(WindowFunction):
    @property
    def dtype(self) -> DataType:
        return integer


class PercentRank(WindowFunction):
    @property
    def dtype(self) -> DataType:
        return double


class CumeDist(WindowFunction):
    @property
    def dtype(self) -> DataType:
        return double


class NTile(WindowFunction):
    def __init__(self, n: int):
        super().__init__()
        assert n >= 1
        self.n = n

    @property
    def dtype(self) -> DataType:
        return integer

    def key(self):
        return ("ntile", self.n)


class Lead(WindowFunction):
    """lead(input, offset, default); Lag is Lead with negative offset."""

    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Expression] = None):
        super().__init__([child] if default is None else [child, default])
        self.offset = offset

    @property
    def input(self) -> Expression:
        return self.children[0]

    @property
    def default(self) -> Optional[Expression]:
        return self.children[1] if len(self.children) > 1 else None

    @property
    def dtype(self) -> DataType:
        return self.children[0].dtype

    @property
    def nullable(self):
        return True

    def key(self):
        return ("lead", self.offset,
                tuple(c.key() for c in self.children))

    def with_children(self, children):
        d = children[1] if len(children) > 1 else None
        node = Lead(children[0], self.offset, d)
        node.__class__ = type(self)
        return node


class Lag(Lead):
    def __init__(self, child: Expression, offset: int = 1,
                 default: Optional[Expression] = None):
        # pyspark: lag(c, -n) == lead(c, n), so negate rather than -abs
        super().__init__(child, -offset, default)


class WindowExpression(Expression):
    """function OVER spec. Children = [function, *partition_exprs,
    *order_exprs] so bottom-up resolution/rewrites reach the spec."""

    def __init__(self, function: Expression, spec: WindowSpecDef):
        assert isinstance(function, (WindowFunction, AggregateFunction)), \
            f"not a window function: {function!r}"
        if spec.frame is not None and not spec.orders:
            raise ValueError(
                "a window frame (rowsBetween/rangeBetween) requires "
                "ORDER BY in the window spec (Spark analysis rule)")
        children = ([function] + list(spec.partitions) +
                    [o.expr for o in spec.orders])
        super().__init__(children)
        self.spec = spec

    @property
    def function(self) -> Expression:
        return self.children[0]

    @property
    def dtype(self) -> DataType:
        return self.function.dtype

    @property
    def nullable(self):
        if isinstance(self.function, AggregateFunction):
            return True
        return self.function.nullable

    def with_children(self, children):
        np_ = len(self.spec.partitions)
        func = children[0]
        parts = children[1:1 + np_]
        oexprs = children[1 + np_:]
        orders = [SortOrder(e, o.ascending, o.nulls_first)
                  for e, o in zip(oexprs, self.spec.orders)]
        return WindowExpression(
            func, WindowSpecDef(parts, orders, self.spec.frame))

    def key(self):
        return ("winexpr", self.function.key(), self.spec.key())

    def __repr__(self):
        return f"{self.function!r} OVER {self.spec.key()!r}"


def contains_window(e: Expression) -> bool:
    if isinstance(e, WindowExpression):
        return True
    return any(contains_window(c) for c in e.children)
