"""String expressions over the padded byte-matrix layout.

Reference coverage: `stringFunctions.scala` rules (Length, Upper, Lower,
Substring, Concat, StartsWith/EndsWith/Contains, ...). cuDF operates on
offset+data string columns; here every op is a fixed-shape computation on
the [rows, max_bytes] uint8 matrix + length vector, which the VPU chews
through directly.

UTF-8 correctness: Length and Substring count *characters* (Spark
semantics) by masking UTF-8 continuation bytes (0b10xxxxxx). Upper/Lower
are ASCII-only on device in v1; columns containing non-ASCII letters give
the same bytes back (documented incompat, like the reference's early
string-op carve-outs in docs/compatibility.md).

Invariant maintained everywhere: bytes at positions >= length are zero
(sort keys and equality rely on it).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression, binary_validity
from spark_rapids_tpu.sqltypes import StringType
from spark_rapids_tpu.sqltypes.datatypes import integer, string as string_t
from spark_rapids_tpu.sqltypes.datatypes import boolean


def _is_continuation(data: jnp.ndarray) -> jnp.ndarray:
    return (data & 0xC0) == 0x80


def _position_mask(col: DeviceColumn) -> jnp.ndarray:
    mb = col.max_bytes
    return jnp.arange(mb, dtype=jnp.int32)[None, :] < col.lengths[:, None]


class Length(Expression):
    """Character count (UTF-8 aware)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        in_str = _position_mask(c)
        chars = in_str & ~_is_continuation(c.data)
        return DeviceColumn(integer, chars.sum(axis=1).astype(jnp.int32),
                            c.validity)


class _CaseMap(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return string_t

    def _map(self, data):
        raise NotImplementedError

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(string_t, self._map(c.data), c.validity,
                            c.lengths)


class Upper(_CaseMap):
    def _map(self, data):
        is_lower = (data >= ord("a")) & (data <= ord("z"))
        return jnp.where(is_lower, data - 32, data).astype(jnp.uint8)


class Lower(_CaseMap):
    def _map(self, data):
        is_upper = (data >= ord("A")) & (data <= ord("Z"))
        return jnp.where(is_upper, data + 32, data).astype(jnp.uint8)


class Substring(Expression):
    """substring(str, pos, len) — 1-based pos, negative from end,
    character-indexed (Spark semantics)."""

    def __init__(self, child, pos: int, length: int = 1 << 30):
        super().__init__([child])
        self.pos = pos
        self.length = length

    @property
    def dtype(self):
        return string_t

    def key(self):
        return ("substr", self.pos, self.length, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        mb = c.max_bytes
        in_str = _position_mask(c)
        is_char = in_str & ~_is_continuation(c.data)
        nchars = is_char.sum(axis=1).astype(jnp.int32)
        # char index of each byte position (0-based), continuation bytes
        # share their lead byte's index
        char_idx = jnp.cumsum(is_char.astype(jnp.int32), axis=1) - 1
        if self.pos > 0:
            start_raw = jnp.full_like(nchars, self.pos - 1)
        elif self.pos == 0:
            start_raw = jnp.zeros_like(nchars)
        else:
            start_raw = nchars + self.pos
        # Spark UTF8String.substringSQL: end uses the UNclamped start, so
        # substring('abc', -5, 2) is '' (end=0), not 'ab'.
        end_char = start_raw + jnp.int32(min(self.length, 1 << 30))
        start_char = jnp.maximum(start_raw, 0)
        keep = in_str & (char_idx >= start_char[:, None]) & \
            (char_idx < end_char[:, None])
        # compact kept bytes to the left: stable sort by ~keep along axis 1
        order = jnp.argsort(~keep, axis=1, stable=True)
        data = jnp.take_along_axis(c.data, order, axis=1)
        new_len = keep.sum(axis=1).astype(jnp.int32)
        pos_m = jnp.arange(mb, dtype=jnp.int32)[None, :] < new_len[:, None]
        data = jnp.where(pos_m, data, 0).astype(jnp.uint8)
        return DeviceColumn(string_t, data, c.validity, new_len)


class Concat(Expression):
    """concat(s1, s2, ...) — null if any input is null (Spark concat)."""

    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def dtype(self):
        return string_t

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        total_mb = sum(c.max_bytes for c in cols)
        mb = max(8, 1 << (total_mb - 1).bit_length())
        n = cols[0].data.shape[0]
        out = jnp.zeros((n, mb), jnp.uint8)
        offset = jnp.zeros((n,), jnp.int32)
        pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
        for c in cols:
            gathered = jnp.take_along_axis(
                jnp.pad(c.data, ((0, 0), (0, max(0, mb - c.max_bytes)))),
                jnp.clip(pos - offset[:, None], 0, mb - 1).astype(jnp.int64),
                axis=1)
            in_span = (pos >= offset[:, None]) & \
                (pos < (offset + c.lengths)[:, None])
            out = jnp.where(in_span, gathered, out)
            offset = offset + c.lengths
        validity = cols[0].validity
        for c in cols[1:]:
            validity = validity & c.validity
        out = out.astype(jnp.uint8)
        return DeviceColumn(string_t, out, validity, offset)


class StartsWith(Expression):
    def __init__(self, child, prefix: str):
        super().__init__([child])
        self.needle = prefix.encode("utf-8")

    @property
    def dtype(self):
        return boolean

    def key(self):
        return ("startswith", self.needle, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        nb = len(self.needle)
        if nb > c.max_bytes:
            return DeviceColumn(boolean,
                                jnp.zeros(c.lengths.shape, bool), c.validity)
        target = jnp.asarray(list(self.needle), jnp.uint8)
        ok = (c.data[:, :nb] == target[None, :]).all(axis=1) & \
            (c.lengths >= nb)
        return DeviceColumn(boolean, ok, c.validity)


class EndsWith(Expression):
    def __init__(self, child, suffix: str):
        super().__init__([child])
        self.needle = suffix.encode("utf-8")

    @property
    def dtype(self):
        return boolean

    def key(self):
        return ("endswith", self.needle, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        nb = len(self.needle)
        if nb > c.max_bytes:
            return DeviceColumn(boolean,
                                jnp.zeros(c.lengths.shape, bool), c.validity)
        target = jnp.asarray(list(self.needle), jnp.uint8)
        start = c.lengths - nb
        pos = jnp.arange(nb, dtype=jnp.int32)[None, :]
        idx = jnp.clip(start[:, None] + pos, 0, c.max_bytes - 1)
        got = jnp.take_along_axis(c.data, idx.astype(jnp.int64), axis=1)
        ok = (got == target[None, :]).all(axis=1) & (c.lengths >= nb)
        return DeviceColumn(boolean, ok, c.validity)


class Contains(Expression):
    def __init__(self, child, needle: str):
        super().__init__([child])
        self.needle = needle.encode("utf-8")

    @property
    def dtype(self):
        return boolean

    def key(self):
        return ("contains", self.needle, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        nb = len(self.needle)
        mb = c.max_bytes
        if nb == 0:
            return DeviceColumn(boolean, jnp.ones(c.lengths.shape, bool),
                                c.validity)
        if nb > mb:
            return DeviceColumn(boolean,
                                jnp.zeros(c.lengths.shape, bool), c.validity)
        # sliding window compare: for each start s in [0, mb-nb], all
        # needle bytes equal — vectorized as nb shifted comparisons.
        ok_at = jnp.ones((c.data.shape[0], mb - nb + 1), bool)
        for i, byte in enumerate(self.needle):
            ok_at = ok_at & (c.data[:, i:i + mb - nb + 1] == byte)
        starts = jnp.arange(mb - nb + 1, dtype=jnp.int32)[None, :]
        in_range = starts <= (c.lengths - nb)[:, None]
        found = (ok_at & in_range).any(axis=1)
        return DeviceColumn(boolean, found, c.validity)


# ---------------------------------------------------------------------------
# Extended string family (reference stringFunctions.scala breadth): trim/pad/
# repeat/reverse/initcap/instr/locate/translate/replace/concat_ws/ascii/chr/
# substring_index. All are fixed-shape VPU computations; variable-length
# outputs use the argsort-compaction idiom (stable sort of ~keep) or
# per-position gather with computed source indices.
# ---------------------------------------------------------------------------

from jax import lax as _lax  # noqa: E402


def _compact_bytes(data, keep, mb_out=None):
    """Keep marked bytes, shifted left per row; returns (data, lengths)."""
    order = jnp.argsort(~keep, axis=1, stable=True)
    out = jnp.take_along_axis(data, order, axis=1)
    new_len = keep.sum(axis=1).astype(jnp.int32)
    mb = data.shape[1]
    pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
    out = jnp.where(pos < new_len[:, None], out, 0).astype(jnp.uint8)
    if mb_out is not None and mb_out != mb:
        out = out[:, :mb_out] if mb_out < mb else jnp.pad(
            out, ((0, 0), (0, mb_out - mb)))
    return out, new_len


def _find_candidates(data, lengths, needle: bytes):
    """[n, mb] bool: a match of `needle` begins at this byte position."""
    n, mb = data.shape
    nb = len(needle)
    if nb == 0 or nb > mb:
        return jnp.zeros((n, mb), bool)
    ok_at = jnp.ones((n, mb - nb + 1), bool)
    for i, byte in enumerate(needle):
        ok_at = ok_at & (data[:, i:i + mb - nb + 1] == byte)
    starts = jnp.arange(mb - nb + 1, dtype=jnp.int32)[None, :]
    ok_at = ok_at & (starts + nb <= lengths[:, None])
    return jnp.pad(ok_at, ((0, 0), (0, nb - 1))) if nb > 1 else ok_at


def _select_nonoverlapping(cand, match_len: int):
    """Greedy left-to-right non-overlapping match selection (the semantics
    of repeated indexOf in Java replace/substring_index)."""
    n, mb = cand.shape
    positions = jnp.arange(mb, dtype=jnp.int32)

    def step(next_free, xs):
        c, i = xs
        sel = c & (i >= next_free)
        return jnp.where(sel, i + match_len, next_free), sel

    _, sels = _lax.scan(step, jnp.zeros((n,), jnp.int32),
                        (cand.T, positions))
    return sels.T


class StringTrimBase(Expression):
    _leading = True
    _trailing = True

    def __init__(self, child, trim_str: str = " "):
        super().__init__([child])
        self.trim_bytes = trim_str.encode("utf-8")

    @property
    def dtype(self):
        return string_t

    def key(self):
        return (type(self).__name__.lower(), self.trim_bytes,
                self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        in_str = _position_mask(c)
        in_set = jnp.zeros(c.data.shape, bool)
        for b in set(self.trim_bytes):
            in_set = in_set | (c.data == b)
        keep = in_str
        if self._leading:
            lead = jnp.cumprod(in_set.astype(jnp.int32), axis=1) > 0
            keep = keep & ~lead
        if self._trailing:
            t = in_set | ~in_str
            rev = jnp.flip(
                jnp.cumprod(jnp.flip(t, axis=1).astype(jnp.int32),
                            axis=1) > 0, axis=1)
            keep = keep & ~rev
        data, lens = _compact_bytes(c.data, keep)
        return DeviceColumn(string_t, data, c.validity, lens)


class StringTrim(StringTrimBase):
    pass


class StringTrimLeft(StringTrimBase):
    _trailing = False


class StringTrimRight(StringTrimBase):
    _leading = False


class _PadBase(Expression):
    """lpad/rpad to `length` characters with an ASCII pad string."""

    def __init__(self, child, length: int, pad: str = " "):
        super().__init__([child])
        self.length = int(length)
        self.pad = pad.encode("utf-8")
        assert all(b < 0x80 for b in self.pad), "ASCII pad strings only"

    @property
    def dtype(self):
        return string_t

    def key(self):
        return (type(self).__name__.lower(), self.length, self.pad,
                self.children[0].key())

    def _layout(self, c):
        target = max(self.length, 0)
        in_str = _position_mask(c)
        is_char = in_str & ~_is_continuation(c.data)
        nchars = is_char.sum(axis=1).astype(jnp.int32)
        char_idx = jnp.cumsum(is_char.astype(jnp.int32), axis=1) - 1
        keep = in_str & (char_idx < target)
        kept_len = keep.sum(axis=1).astype(jnp.int32)
        npad = jnp.maximum(target - nchars, 0).astype(jnp.int32)
        mb_out = max(8, 1 << max(0, target + c.max_bytes - 1).bit_length())
        lp = max(len(self.pad), 1)
        pos = jnp.arange(mb_out, dtype=jnp.int32)
        padvec = jnp.asarray(
            [(self.pad or b" ")[i % lp] for i in range(mb_out)], jnp.uint8)
        data_wide = jnp.pad(c.data, ((0, 0), (0, mb_out - c.max_bytes))) \
            if mb_out > c.max_bytes else c.data[:, :mb_out]
        return target, kept_len, npad, mb_out, pos, padvec, data_wide


class StringLPad(_PadBase):
    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        (target, kept_len, npad, mb_out, pos, padvec,
         data_wide) = self._layout(c)
        src_idx = jnp.clip(pos[None, :] - npad[:, None], 0, mb_out - 1)
        src = jnp.take_along_axis(data_wide, src_idx.astype(jnp.int64),
                                  axis=1)
        out_len = npad + kept_len
        out = jnp.where(pos[None, :] < npad[:, None], padvec[None, :], src)
        out = jnp.where(pos[None, :] < out_len[:, None], out, 0)
        return DeviceColumn(string_t, out.astype(jnp.uint8), c.validity,
                            out_len)


class StringRPad(_PadBase):
    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        (target, kept_len, npad, mb_out, pos, padvec,
         data_wide) = self._layout(c)
        # pad characters appended after the kept prefix; pad cycle restarts
        # at the append point (Java StringUtils behavior)
        pad_idx = jnp.clip(pos[None, :] - kept_len[:, None], 0, mb_out - 1)
        lp = max(len(self.pad), 1)
        padmat = jnp.asarray(list(self.pad or b" "), jnp.uint8)[
            pad_idx % lp]
        out = jnp.where(pos[None, :] < kept_len[:, None], data_wide, padmat)
        out_len = kept_len + npad
        out = jnp.where(pos[None, :] < out_len[:, None], out, 0)
        return DeviceColumn(string_t, out.astype(jnp.uint8), c.validity,
                            out_len)


class StringRepeat(Expression):
    def __init__(self, child, times: int):
        super().__init__([child])
        self.times = int(times)

    @property
    def dtype(self):
        return string_t

    def key(self):
        return ("repeat", self.times, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        n = max(self.times, 0)
        if n == 0:
            cap = c.data.shape[0]
            return DeviceColumn(string_t, jnp.zeros((cap, 8), jnp.uint8),
                                c.validity, jnp.zeros((cap,), jnp.int32))
        mb_out = max(8, 1 << max(0, c.max_bytes * n - 1).bit_length())
        pos = jnp.arange(mb_out, dtype=jnp.int32)[None, :]
        safe_len = jnp.maximum(c.lengths, 1)[:, None]
        src_idx = jnp.clip(pos % safe_len, 0, c.max_bytes - 1)
        src = jnp.take_along_axis(c.data, src_idx.astype(jnp.int64), axis=1)
        out_len = (c.lengths * n).astype(jnp.int32)
        out = jnp.where(pos < out_len[:, None], src, 0).astype(jnp.uint8)
        return DeviceColumn(string_t, out, c.validity, out_len)


class StringReverse(Expression):
    """Character-aware (UTF-8) reverse."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return string_t

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        mb = c.max_bytes
        in_str = _position_mask(c)
        is_char = in_str & ~_is_continuation(c.data)
        nchars = is_char.sum(axis=1).astype(jnp.int32)
        char_idx = jnp.cumsum(is_char.astype(jnp.int32), axis=1) - 1
        pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
        lead_pos = _lax.cummax(jnp.where(is_char, pos, -1), axis=1)
        within = pos - lead_pos
        key = (nchars[:, None] - 1 - char_idx) * mb + within
        key = jnp.where(in_str, key, jnp.int32(1 << 30))
        order = jnp.argsort(key, axis=1, stable=True)
        out = jnp.take_along_axis(c.data, order, axis=1)
        out = jnp.where(pos < c.lengths[:, None], out, 0).astype(jnp.uint8)
        return DeviceColumn(string_t, out, c.validity, c.lengths)


class InitCap(Expression):
    """Uppercase first letter of each space-delimited word; lowercase the
    rest (ASCII letters)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return string_t

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        d = c.data
        prev_space = jnp.concatenate(
            [jnp.ones((d.shape[0], 1), bool), d[:, :-1] == 0x20], axis=1)
        is_up = (d >= 0x41) & (d <= 0x5A)
        is_lo = (d >= 0x61) & (d <= 0x7A)
        lowered = jnp.where(is_up, d + 32, d)
        out = jnp.where(prev_space & is_lo, d - 32,
                        jnp.where(~prev_space, lowered, d))
        return DeviceColumn(string_t, out.astype(jnp.uint8), c.validity,
                            c.lengths)


class StringInstr(Expression):
    """instr(str, substr): 1-based char position of first match, 0 if
    absent, 1 for empty substr."""

    def __init__(self, child, substr: str):
        super().__init__([child])
        self.needle = substr.encode("utf-8")

    @property
    def dtype(self):
        return integer

    def key(self):
        return ("instr", self.needle, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(integer, _first_match_charpos(c, self.needle, 0),
                            c.validity)


class StringLocate(Expression):
    """locate(substr, str, start): like instr but from a 1-based char
    start; start <= 0 -> 0."""

    def __init__(self, child, substr: str, start: int = 1):
        super().__init__([child])
        self.needle = substr.encode("utf-8")
        self.start = int(start)

    @property
    def dtype(self):
        return integer

    def key(self):
        return ("locate", self.needle, self.start, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        if self.start <= 0:
            return DeviceColumn(
                integer, jnp.zeros(c.lengths.shape, jnp.int32), c.validity)
        r = _first_match_charpos(c, self.needle, self.start - 1)
        return DeviceColumn(integer, r, c.validity)


def _first_match_charpos(c, needle: bytes, min_char: int) -> jnp.ndarray:
    """1-based char position of first occurrence at char >= min_char."""
    mb = c.max_bytes
    in_str = _position_mask(c)
    is_char = in_str & ~_is_continuation(c.data)
    char_idx = jnp.cumsum(is_char.astype(jnp.int32), axis=1) - 1
    nchars = is_char.sum(axis=1).astype(jnp.int32)
    if len(needle) == 0:
        hit = jnp.minimum(jnp.int32(min_char), nchars) + 1
        return jnp.where(min_char <= nchars, hit, 0).astype(jnp.int32)
    cand = _find_candidates(c.data, c.lengths, needle)
    cand = cand & (char_idx >= min_char) & is_char
    found = cand.any(axis=1)
    first_byte = jnp.argmax(cand, axis=1)
    first_char = jnp.take_along_axis(
        char_idx, first_byte[:, None].astype(jnp.int64), axis=1)[:, 0]
    return jnp.where(found, first_char + 1, 0).astype(jnp.int32)


class StringTranslate(Expression):
    """translate(str, match, replace): per-byte LUT; chars in `match`
    beyond len(replace) are deleted (ASCII alphabets)."""

    def __init__(self, child, matching: str, replace: str):
        super().__init__([child])
        self.matching = matching.encode("utf-8")
        self.replace = replace.encode("utf-8")

    @property
    def dtype(self):
        return string_t

    def key(self):
        return ("translate", self.matching, self.replace,
                self.children[0].key())

    def eval(self, ctx):
        import numpy as _np

        c = self.children[0].eval(ctx)
        lut = _np.arange(256, dtype=_np.uint8)
        delete = _np.zeros(256, dtype=bool)
        seen = set()
        for i, m in enumerate(self.matching):
            if m in seen:  # first mapping wins (Spark)
                continue
            seen.add(m)
            if i < len(self.replace):
                lut[m] = self.replace[i]
            else:
                delete[m] = True
        mapped = jnp.asarray(lut)[c.data.astype(jnp.int32)]
        in_str = _position_mask(c)
        keep = in_str & ~jnp.asarray(delete)[c.data.astype(jnp.int32)]
        data, lens = _compact_bytes(mapped, keep)
        return DeviceColumn(string_t, data, c.validity, lens)


class StringReplace(Expression):
    """replace(str, search, replacement): all non-overlapping occurrences,
    leftmost-greedy (Java String.replace)."""

    def __init__(self, child, search: str, replacement: str = ""):
        super().__init__([child])
        self.search = search.encode("utf-8")
        self.replacement = replacement.encode("utf-8")

    @property
    def dtype(self):
        return string_t

    def key(self):
        return ("replace", self.search, self.replacement,
                self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        ls, lr = len(self.search), len(self.replacement)
        if ls == 0:
            return c
        mb = c.max_bytes
        cand = _find_candidates(c.data, c.lengths, self.search)
        sel = _select_nonoverlapping(cand, ls)
        pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
        # covered[i] = i falls strictly inside a selected match
        sel_start = jnp.where(sel, pos, -(1 << 20))
        last_start = _lax.cummax(sel_start, axis=1)
        covered = (pos < last_start + ls) & (last_start >= 0)
        in_str = _position_mask(c)
        emit_n = jnp.where(sel, lr,
                           jnp.where(covered | ~in_str, 0, 1))
        offsets = jnp.cumsum(emit_n, axis=1) - emit_n  # exclusive
        out_len = emit_n.sum(axis=1).astype(jnp.int32)
        e = max(lr, 1)
        # emission matrix [n, mb, e]: replacement bytes at selected starts,
        # the original byte in slot 0 otherwise
        repl = jnp.asarray(list(self.replacement or b"\x00"), jnp.uint8)
        slot = jnp.arange(e, dtype=jnp.int32)
        emat = jnp.where(sel[:, :, None], repl[None, None, :e],
                         c.data[:, :, None])
        emask = slot[None, None, :] < emit_n[:, :, None]
        flat_bytes = emat.reshape(emat.shape[0], mb * e)
        flat_mask = emask.reshape(emat.shape[0], mb * e)
        need = mb * max(1, lr)
        mb_out = max(8, 1 << max(0, need - 1).bit_length())
        data, lens = _compact_bytes(flat_bytes, flat_mask, mb_out=mb_out)
        return DeviceColumn(string_t, data, c.validity, lens)


class ConcatWs(Expression):
    """concat_ws(sep, s1, s2, ...): null inputs are skipped; result is
    non-null (Spark semantics with a literal separator)."""

    def __init__(self, sep: str, *exprs):
        super().__init__(list(exprs))
        self.sep = sep.encode("utf-8")

    @property
    def dtype(self):
        return string_t

    @property
    def nullable(self):
        return False

    def key(self):
        return ("concat_ws", self.sep,
                tuple(c.key() for c in self.children))

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        lsep = len(self.sep)
        total = sum(c.max_bytes for c in cols) + lsep * max(
            0, len(cols) - 1)
        mb = max(8, 1 << max(0, total - 1).bit_length())
        n = cols[0].data.shape[0]
        out = jnp.zeros((n, mb), jnp.uint8)
        offset = jnp.zeros((n,), jnp.int32)
        emitted_any = jnp.zeros((n,), bool)
        pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
        sep_mat = jnp.asarray(list(self.sep or b"\x00"), jnp.uint8)
        for c in cols:
            use = c.validity
            # separator first (only between two emitted values)
            if lsep:
                sep_here = use & emitted_any
                sep_off = offset
                idx = jnp.clip(pos - sep_off[:, None], 0, max(lsep - 1, 0))
                span = (pos >= sep_off[:, None]) & \
                    (pos < (sep_off + lsep)[:, None]) & sep_here[:, None]
                out = jnp.where(span, sep_mat[idx], out)
                offset = jnp.where(sep_here, offset + lsep, offset)
            gathered = jnp.take_along_axis(
                jnp.pad(c.data, ((0, 0), (0, max(0, mb - c.max_bytes)))),
                jnp.clip(pos - offset[:, None], 0, mb - 1).astype(jnp.int64),
                axis=1)
            span = (pos >= offset[:, None]) & \
                (pos < (offset + c.lengths)[:, None]) & use[:, None]
            out = jnp.where(span, gathered, out)
            offset = jnp.where(use, offset + c.lengths, offset)
            emitted_any = emitted_any | use
        return DeviceColumn(string_t, out.astype(jnp.uint8),
                            jnp.ones((n,), bool), offset)


class Ascii(Expression):
    """ascii(str): codepoint of the first character (first byte for
    ASCII); 0 for empty string."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        first = c.data[:, 0].astype(jnp.int32)
        return DeviceColumn(integer,
                            jnp.where(c.lengths > 0, first, 0), c.validity)


class Chr(Expression):
    """chr(n): the character for code n & 0xFF. Spark: n < 0 -> "";
    (n & 0xFF) == 0 -> the 1-char NUL string; 128-255 encode as 2-byte
    UTF-8."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return string_t

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        code = (c.data.astype(jnp.int64) & 0xFF).astype(jnp.int32)
        neg = c.data.astype(jnp.int64) < 0
        two_byte = code >= 0x80
        n = c.data.shape[0]
        b0 = jnp.where(two_byte, 0xC0 | (code >> 6), code)
        b1 = jnp.where(two_byte, 0x80 | (code & 0x3F), 0)
        data = jnp.zeros((n, 8), jnp.uint8)
        data = data.at[:, 0].set(jnp.where(neg, 0, b0).astype(jnp.uint8))
        data = data.at[:, 1].set(jnp.where(neg, 0, b1).astype(jnp.uint8))
        lens = jnp.where(neg, 0, jnp.where(two_byte, 2, 1)).astype(jnp.int32)
        return DeviceColumn(string_t, data, c.validity, lens)


class SubstringIndex(Expression):
    """substring_index(str, delim, count).

    Known incompat: for negative counts with self-overlapping delimiters
    (e.g. delim 'aa' in 'aaa') occurrences are counted left-greedy while
    Spark scans lastIndexOf from the right; results agree whenever the
    delimiter does not overlap itself."""

    def __init__(self, child, delim: str, count: int):
        super().__init__([child])
        self.delim = delim.encode("utf-8")
        self.count = int(count)

    @property
    def dtype(self):
        return string_t

    def key(self):
        return ("substring_index", self.delim, self.count,
                self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        cnt = self.count
        ld = len(self.delim)
        cap = c.data.shape[0]
        if cnt == 0 or ld == 0:
            return DeviceColumn(string_t, jnp.zeros((cap, 8), jnp.uint8),
                                c.validity, jnp.zeros((cap,), jnp.int32))
        mb = c.max_bytes
        cand = _find_candidates(c.data, c.lengths, self.delim)
        sel = _select_nonoverlapping(cand, ld)
        occ = jnp.cumsum(sel.astype(jnp.int32), axis=1)
        total = occ[:, -1]
        pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
        in_str = _position_mask(c)
        if cnt > 0:
            # cut before the cnt-th occurrence
            is_kth = sel & (occ == cnt)
            has = total >= cnt
            cut = jnp.where(has,
                            jnp.where(is_kth, pos, mb).min(axis=1),
                            c.lengths).astype(jnp.int32)
            keep = in_str & (pos < cut[:, None])
        else:
            k = -cnt
            # keep after the (total-k+1)-th occurrence's end
            target = total - k + 1
            is_kth = sel & (occ == target[:, None]) & (target[:, None] >= 1)
            has = total >= k
            start = jnp.where(
                has, jnp.where(is_kth, pos, -1).max(axis=1) + ld,
                0).astype(jnp.int32)
            keep = in_str & (pos >= start[:, None])
        data, lens = _compact_bytes(c.data, keep)
        return DeviceColumn(string_t, data, c.validity, lens)
