"""String expressions over the padded byte-matrix layout.

Reference coverage: `stringFunctions.scala` rules (Length, Upper, Lower,
Substring, Concat, StartsWith/EndsWith/Contains, ...). cuDF operates on
offset+data string columns; here every op is a fixed-shape computation on
the [rows, max_bytes] uint8 matrix + length vector, which the VPU chews
through directly.

UTF-8 correctness: Length and Substring count *characters* (Spark
semantics) by masking UTF-8 continuation bytes (0b10xxxxxx). Upper/Lower
are ASCII-only on device in v1; columns containing non-ASCII letters give
the same bytes back (documented incompat, like the reference's early
string-op carve-outs in docs/compatibility.md).

Invariant maintained everywhere: bytes at positions >= length are zero
(sort keys and equality rely on it).
"""

from __future__ import annotations

import jax.numpy as jnp

from spark_rapids_tpu.columnar.batch import DeviceColumn
from spark_rapids_tpu.expr.core import Expression, binary_validity
from spark_rapids_tpu.sqltypes import StringType
from spark_rapids_tpu.sqltypes.datatypes import integer, string as string_t
from spark_rapids_tpu.sqltypes.datatypes import boolean


def _is_continuation(data: jnp.ndarray) -> jnp.ndarray:
    return (data & 0xC0) == 0x80


def _position_mask(col: DeviceColumn) -> jnp.ndarray:
    mb = col.max_bytes
    return jnp.arange(mb, dtype=jnp.int32)[None, :] < col.lengths[:, None]


class Length(Expression):
    """Character count (UTF-8 aware)."""

    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return integer

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        in_str = _position_mask(c)
        chars = in_str & ~_is_continuation(c.data)
        return DeviceColumn(integer, chars.sum(axis=1).astype(jnp.int32),
                            c.validity)


class _CaseMap(Expression):
    def __init__(self, child):
        super().__init__([child])

    @property
    def dtype(self):
        return string_t

    def _map(self, data):
        raise NotImplementedError

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        return DeviceColumn(string_t, self._map(c.data), c.validity,
                            c.lengths)


class Upper(_CaseMap):
    def _map(self, data):
        is_lower = (data >= ord("a")) & (data <= ord("z"))
        return jnp.where(is_lower, data - 32, data).astype(jnp.uint8)


class Lower(_CaseMap):
    def _map(self, data):
        is_upper = (data >= ord("A")) & (data <= ord("Z"))
        return jnp.where(is_upper, data + 32, data).astype(jnp.uint8)


class Substring(Expression):
    """substring(str, pos, len) — 1-based pos, negative from end,
    character-indexed (Spark semantics)."""

    def __init__(self, child, pos: int, length: int = 1 << 30):
        super().__init__([child])
        self.pos = pos
        self.length = length

    @property
    def dtype(self):
        return string_t

    def key(self):
        return ("substr", self.pos, self.length, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        mb = c.max_bytes
        in_str = _position_mask(c)
        is_char = in_str & ~_is_continuation(c.data)
        nchars = is_char.sum(axis=1).astype(jnp.int32)
        # char index of each byte position (0-based), continuation bytes
        # share their lead byte's index
        char_idx = jnp.cumsum(is_char.astype(jnp.int32), axis=1) - 1
        if self.pos > 0:
            start_raw = jnp.full_like(nchars, self.pos - 1)
        elif self.pos == 0:
            start_raw = jnp.zeros_like(nchars)
        else:
            start_raw = nchars + self.pos
        # Spark UTF8String.substringSQL: end uses the UNclamped start, so
        # substring('abc', -5, 2) is '' (end=0), not 'ab'.
        end_char = start_raw + jnp.int32(min(self.length, 1 << 30))
        start_char = jnp.maximum(start_raw, 0)
        keep = in_str & (char_idx >= start_char[:, None]) & \
            (char_idx < end_char[:, None])
        # compact kept bytes to the left: stable sort by ~keep along axis 1
        order = jnp.argsort(~keep, axis=1, stable=True)
        data = jnp.take_along_axis(c.data, order, axis=1)
        new_len = keep.sum(axis=1).astype(jnp.int32)
        pos_m = jnp.arange(mb, dtype=jnp.int32)[None, :] < new_len[:, None]
        data = jnp.where(pos_m, data, 0).astype(jnp.uint8)
        return DeviceColumn(string_t, data, c.validity, new_len)


class Concat(Expression):
    """concat(s1, s2, ...) — null if any input is null (Spark concat)."""

    def __init__(self, *exprs):
        super().__init__(list(exprs))

    @property
    def dtype(self):
        return string_t

    def eval(self, ctx):
        cols = [c.eval(ctx) for c in self.children]
        total_mb = sum(c.max_bytes for c in cols)
        mb = max(8, 1 << (total_mb - 1).bit_length())
        n = cols[0].data.shape[0]
        out = jnp.zeros((n, mb), jnp.uint8)
        offset = jnp.zeros((n,), jnp.int32)
        pos = jnp.arange(mb, dtype=jnp.int32)[None, :]
        for c in cols:
            gathered = jnp.take_along_axis(
                jnp.pad(c.data, ((0, 0), (0, max(0, mb - c.max_bytes)))),
                jnp.clip(pos - offset[:, None], 0, mb - 1).astype(jnp.int64),
                axis=1)
            in_span = (pos >= offset[:, None]) & \
                (pos < (offset + c.lengths)[:, None])
            out = jnp.where(in_span, gathered, out)
            offset = offset + c.lengths
        validity = cols[0].validity
        for c in cols[1:]:
            validity = validity & c.validity
        out = out.astype(jnp.uint8)
        return DeviceColumn(string_t, out, validity, offset)


class StartsWith(Expression):
    def __init__(self, child, prefix: str):
        super().__init__([child])
        self.needle = prefix.encode("utf-8")

    @property
    def dtype(self):
        return boolean

    def key(self):
        return ("startswith", self.needle, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        nb = len(self.needle)
        if nb > c.max_bytes:
            return DeviceColumn(boolean,
                                jnp.zeros(c.lengths.shape, bool), c.validity)
        target = jnp.asarray(list(self.needle), jnp.uint8)
        ok = (c.data[:, :nb] == target[None, :]).all(axis=1) & \
            (c.lengths >= nb)
        return DeviceColumn(boolean, ok, c.validity)


class EndsWith(Expression):
    def __init__(self, child, suffix: str):
        super().__init__([child])
        self.needle = suffix.encode("utf-8")

    @property
    def dtype(self):
        return boolean

    def key(self):
        return ("endswith", self.needle, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        nb = len(self.needle)
        if nb > c.max_bytes:
            return DeviceColumn(boolean,
                                jnp.zeros(c.lengths.shape, bool), c.validity)
        target = jnp.asarray(list(self.needle), jnp.uint8)
        start = c.lengths - nb
        pos = jnp.arange(nb, dtype=jnp.int32)[None, :]
        idx = jnp.clip(start[:, None] + pos, 0, c.max_bytes - 1)
        got = jnp.take_along_axis(c.data, idx.astype(jnp.int64), axis=1)
        ok = (got == target[None, :]).all(axis=1) & (c.lengths >= nb)
        return DeviceColumn(boolean, ok, c.validity)


class Contains(Expression):
    def __init__(self, child, needle: str):
        super().__init__([child])
        self.needle = needle.encode("utf-8")

    @property
    def dtype(self):
        return boolean

    def key(self):
        return ("contains", self.needle, self.children[0].key())

    def eval(self, ctx):
        c = self.children[0].eval(ctx)
        nb = len(self.needle)
        mb = c.max_bytes
        if nb == 0:
            return DeviceColumn(boolean, jnp.ones(c.lengths.shape, bool),
                                c.validity)
        if nb > mb:
            return DeviceColumn(boolean,
                                jnp.zeros(c.lengths.shape, bool), c.validity)
        # sliding window compare: for each start s in [0, mb-nb], all
        # needle bytes equal — vectorized as nb shifted comparisons.
        ok_at = jnp.ones((c.data.shape[0], mb - nb + 1), bool)
        for i, byte in enumerate(self.needle):
            ok_at = ok_at & (c.data[:, i:i + mb - nb + 1] == byte)
        starts = jnp.arange(mb - nb + 1, dtype=jnp.int32)[None, :]
        in_range = starts <= (c.lengths - nb)[:, None]
        found = (ok_at & in_range).any(axis=1)
        return DeviceColumn(boolean, found, c.validity)
