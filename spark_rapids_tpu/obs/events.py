"""Typed, thread-safe query-event bus — the observability substrate.

The reference plugin threads GpuMetric levels and NVTX ranges through
every operator and ships standalone qualification/profiling tools that
read Spark event logs. This module unifies that surface for the engine:
every layer (planner, scheduler, shuffle, spill catalog, compile cache,
degradation ladder, chaos harness) emits TYPED events into one process
bus; span trees (obs/spans.py), the JSONL event log (obs/eventlog.py),
the qualification/profile reports (obs/report.py) and the Prometheus
dump (obs/prom.py) are all views over this stream.

Schema: every event is a flat JSON object carrying the envelope keys
`event` (type name), `seq` (bus-monotonic), `ts` (unix seconds),
`schemaVersion`, and `queryId` (the enclosing query, 0 outside one),
plus per-type payload fields. Task-scoped emissions (operator spans
inside a scheduler attempt) additionally inherit `stage`/`task`/
`attempt`/`speculative` from the thread's task scope, which is how the
span builder hangs operator spans under the right task attempt.

Emitters call the module-level `emit(...)`, which is a None-check when
no session installed a bus (`spark.rapids.tpu.obs.enabled=false`, or no
session yet) — hot paths pay nothing when tracing is off.
"""

from __future__ import annotations

import contextlib
import itertools
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

SCHEMA_VERSION = 1

#: Stable event-type registry: name -> payload field summary (doc'd in
#: docs/observability.md; eventlog validation accepts only these).
EVENT_TYPES: Dict[str, str] = {
    "query.start": "queryId",
    "query.end": "engine, status, fallbacks, degradations",
    "plan.placement": "node, depth, onDevice, reason",
    "stage.start": "stage, name, tasks",
    "stage.end": "stage, name, status",
    "task.attempt.start": "stage, task, attempt, worker, speculative",
    "task.attempt.end": "stage, task, attempt, status, wallMs, rows",
    "operator.span": "operator, metric, wallNs, deviceNs, rows",
    "shuffle.write": "shuffleId, reducePid, bytes, staged",
    "shuffle.fetch": "shuffleId, reducePid, blocks, bytes",
    "shuffle.retry": "shuffleId, reducePid, block",
    "spill": "component, direction, fromTier, toTier, bytes",
    "transfer": "direction (h2d|d2h|spill-disk|shuffle|ici|dcn), "
                "site, bytes, ns",
    "telemetry.summary":
        "bytesMoved, bytesMovedTotal, hbmPeakBytes, rooflineFrac, "
        "linkFrac, bytesPerOutputRow, wallMs",
    "compile": "kind (miss|hit|warm|quarantine), seconds",
    "degrade": "kind, from, to, reason",
    "chaos": "site",
    "admission.queued": "queryId, depth, running",
    "admission.admitted": "queryId, waitMs",
    "admission.shed": "queryId, reason, running",
    "admission.cancelled": "queryId, reason, latencyMs",
    "admission.deadline": "queryId, reason, latencyMs",
    "admission.quarantined": "queryId, reason, crashes",
    "sanitizer.deadlock": "cycle, victim, policy",
    "sanitizer.inversion": "first, second, detail",
    "device.fatal": "site, epoch, error",
    "device.fence": "epoch, cause, inFlight",
    "device.recovery":
        "epoch, ms, drained, restorableBuffers, droppedBuffers",
    "chip.fence": "device, chipEpoch, cause",
    "chip.unfence": "device, chipEpoch",
    "chip.recovery": "device, chipEpoch, shards, survivors, ms",
    "host.fence": "host, devices, chipEpoch, cause",
    "host.unfence": "host, devices, chipEpoch",
    "host.recovery":
        "host, devices, chipEpoch, hosts, survivorHosts, shards, "
        "survivors, ms",
    "ici.retry": "detail, left",
    "dcn.retry": "detail, left",
    "multihost.init": "processes, processIndex, devices, localDevices",
    "serve.connect": "tenant, priorityClass, addr",
    "serve.disconnect": "tenant, queries, bytesOut",
    "serve.query":
        "tenant, priorityClass, planCache, status, rows, wallMs",
    "serve.shed": "tenant, reason",
    "serve.drain": "phase, inFlight, connections",
    "serve.dedupe": "tenant, requestId, outcome (replay|joined|evicted)",
    "serve.escalate": "inFlight, connections",
    "serve.retry": "site, attempt, delayMs",
    "fleet.replica": "name, phase (spawn|ready|exit|restart|giveup), "
                     "pid, port, restarts",
    "fleet.health": "replica, ready, consecutiveFailures",
    "fleet.failover":
        "requestId, tenant, fromReplica, toReplica, reason",
    "fleet.drain": "phase, replicas",
    "stream.start": "partitions, windowBytes, prefetchThreads",
    "stream.partition": "unit, rows, bytes, retired",
    "stream.window": "action (admit|evict|spill|recover|mesh), bytes, "
                     "inUse",
    "stream.end": "partitions, retired, recoveries, windowPeakBytes, "
                  "overlapFraction",
    "write.start": "jobId, path, format, mode, tasks",
    "write.task": "jobId, task, files, bytes, rows",
    "write.commit": "jobId, files, bytes, rows, commitMs, swapped",
    "write.abort": "jobId, reason",
    "write.options": "format, ignored",
    "write.conflict": "path, kind, error",
}

#: Envelope keys present on EVERY event (eventlog validation contract).
REQUIRED_KEYS = ("event", "seq", "ts", "schemaVersion", "queryId")


class EventBus:
    """Synchronous fan-out bus. Emission is serialized under one lock
    so subscribers observe a total order matching `seq` — the property
    the span builder and the event-log writer both rely on. Subscriber
    exceptions are counted, never propagated into the query."""

    def __init__(self):
        self._lock = threading.Lock()
        self._subs: List[Callable[[dict], None]] = []
        self._seq = 0
        self.counts: Dict[str, int] = {}
        self.subscriber_errors = 0

    def subscribe(self, fn: Callable[[dict], None]) -> Callable:
        with self._lock:
            self._subs.append(fn)
        return fn

    def unsubscribe(self, fn: Callable[[dict], None]) -> None:
        with self._lock:
            if fn in self._subs:
                self._subs.remove(fn)

    def emit(self, event: str, **fields) -> dict:
        ev = {"event": event, "schemaVersion": SCHEMA_VERSION,
              "queryId": current_query_id(), "ts": round(time.time(), 6)}
        ctx = task_context()
        if ctx:
            ev.update(ctx)
        ev.update(fields)
        with self._lock:
            self._seq += 1
            ev["seq"] = self._seq
            self.counts[event] = self.counts.get(event, 0) + 1
            for fn in list(self._subs):
                try:
                    fn(ev)
                except Exception:
                    self.subscriber_errors += 1
        return ev


class EventHistory:
    """Ring-buffer subscriber retaining recent events so live-session
    reports (obs/report.py) work without an event log."""

    def __init__(self, capacity: int = 100_000):
        self._events: deque = deque(maxlen=max(100, int(capacity)))
        self._lock = threading.Lock()

    def __call__(self, ev: dict) -> None:
        with self._lock:
            self._events.append(ev)

    def events(self, query_id: Optional[int] = None) -> List[dict]:
        with self._lock:
            evs = list(self._events)
        if query_id is None:
            return evs
        return [e for e in evs if e.get("queryId") == query_id]

    def last_query_id(self) -> Optional[int]:
        with self._lock:
            for e in reversed(self._events):
                if e.get("queryId"):
                    return e["queryId"]
        return None


# ------------------------------------------------------ process wiring

_bus: Optional[EventBus] = None
_install_lock = threading.Lock()


def install(bus: Optional[EventBus]) -> Optional[EventBus]:
    """Make `bus` the process emit target (session lifecycle hook)."""
    global _bus
    with _install_lock:
        _bus = bus
    return bus


def uninstall(bus: EventBus) -> None:
    """Remove `bus` if it is still the active one (a newer session's
    bus must not be torn down by an older session's stop())."""
    global _bus
    with _install_lock:
        if _bus is bus:
            _bus = None


def get() -> Optional[EventBus]:
    return _bus


def armed() -> bool:
    return _bus is not None


def emit(event: str, **fields) -> None:
    """Hot-path entry: one None-check when tracing is off."""
    bus = _bus
    if bus is not None:
        bus.emit(event, **fields)


# ------------------------------------------------------- query context
#
# THREAD-LOCAL: each submitting thread owns its query scope, so
# concurrent queries through one session get distinct ids (the
# multi-tenant governance unit, runtime/admission.py). Nested collects
# on the same thread (cache materialization, writes that read) still
# fold into the enclosing query's stream; scheduler pool threads
# inherit the id through the task scope below.

_query_counter = itertools.count(1)
_query_tls = threading.local()


def allocate_query_id() -> int:
    """Reserve a query id BEFORE the query scope opens — the admission
    controller names queued/shed queries by the same id their events
    and span tree will carry once (if) they run."""
    return next(_query_counter)


def begin_query(qid: Optional[int] = None) -> int:
    """Enter a query scope on this thread; emits `query.start` for the
    OUTERMOST scope only. A preallocated `qid` (admission) is honored
    at the outermost scope; nested scopes keep the enclosing id."""
    depth = getattr(_query_tls, "depth", 0)
    _query_tls.depth = depth + 1
    if depth == 0:
        _query_tls.qid = qid if qid is not None else next(_query_counter)
        emit("query.start")
    return _query_tls.qid


def finish_query(qid: int, **fields) -> None:
    """Leave a query scope; the outermost exit emits `query.end` with
    the caller's summary fields (engine, status, ...)."""
    depth = max(0, getattr(_query_tls, "depth", 0) - 1)
    _query_tls.depth = depth
    if depth == 0:
        # emit BEFORE clearing the id so the end event carries it
        emit("query.end", **fields)
        _query_tls.qid = 0


def current_query_id() -> int:
    return getattr(_query_tls, "qid", 0)


def effective_query_id() -> int:
    """Query attribution for code that may run in a scheduler pool
    thread: the task scope's captured query id first, else this
    thread's own query scope (memory quotas and semaphore diagnostics
    resolve their owner through this)."""
    ctx = task_context()
    if ctx and ctx.get("queryId"):
        return ctx["queryId"]
    return current_query_id()


# -------------------------------------------------------- task context

_task_ctx = threading.local()


@contextlib.contextmanager
def task_scope(stage: int, task: int, attempt: int,
               speculative: bool = False,
               query_id: Optional[int] = None):
    """Tag the current thread with a scheduler attempt identity; events
    emitted inside (operator spans above all) inherit it. Nests: an
    exchange map stage running inside a result task re-tags to the
    inner attempt and restores on exit. `query_id` carries the
    submitting thread's (thread-local) query scope into pool threads —
    emit() lets it override the pool thread's own empty scope."""
    prev = getattr(_task_ctx, "ctx", None)
    ctx = {"stage": stage, "task": task, "attempt": attempt,
           "speculative": bool(speculative)}
    if query_id:
        ctx["queryId"] = query_id
    _task_ctx.ctx = ctx
    try:
        yield
    finally:
        _task_ctx.ctx = prev


def task_context() -> dict:
    return getattr(_task_ctx, "ctx", None) or {}


# ------------------------------------------------------- plan emission

def emit_plan_placement(meta) -> None:
    """Walk a tagged PlanMeta tree (plan/overrides.py) and emit one
    `plan.placement` event per node — the structured twin of
    explain_potential_tpu_plan: `reason` is the exact '; '-joined
    string the NOT_ON_TPU report prints, which is what lets
    obs.report.qualification() match it verbatim."""
    if not armed():
        return

    def walk(m, depth: int) -> None:
        on_dev = m.can_run_on_device
        emit("plan.placement", node=type(m.node).__name__, depth=depth,
             onDevice=bool(on_dev),
             reason=None if on_dev else "; ".join(m.reasons))
        for c in m.children:
            walk(c, depth + 1)

    walk(meta, 0)
