"""Unified metric registry views.

Every failure-domain and performance counter in the engine lives in its
owning module (faults, backoff, shuffle manager, stage scheduler,
degradation ladder, compile ledger, semaphore, spill catalog); this
module is the ONE place that assembles them. `session.robustness_metrics`
and bench.py's robustness block are views over `robustness_snapshot()`
(their keys are a stable contract — test_chaos.py/test_scheduler.py pin
them), and the Prometheus dump (obs/prom.py) flattens
`unified_snapshot()`.
"""

from __future__ import annotations

from typing import Dict, Optional


def robustness_snapshot() -> dict:
    """One snapshot of every failure-domain counter (PR 2/3): chaos
    injections per site, backoff retries per domain, shuffle
    fetch/checksum recoveries + orphaned/discarded blocks,
    stage-scheduler recoveries, degradation-ladder demotions +
    circuit-breaker state, quarantined compile artifacts, and
    semaphore timeouts. Key layout is pinned by existing tests."""
    from spark_rapids_tpu.runtime import admission as _adm
    from spark_rapids_tpu.runtime import backoff, degrade, faults
    from spark_rapids_tpu.runtime import device_monitor as _dm
    from spark_rapids_tpu.runtime import memory as _mem
    from spark_rapids_tpu.runtime import sanitizer as _san
    from spark_rapids_tpu.runtime import scheduler as _sched
    from spark_rapids_tpu.runtime import semaphore as sem
    from spark_rapids_tpu.runtime.compile_cache import stats
    from spark_rapids_tpu.shuffle.manager import get_shuffle_manager

    mgr = get_shuffle_manager()
    cat = _mem._catalog
    return {
        "chaos": faults.counters(),
        "retries": backoff.counters(),
        "shuffle": {"fetchRetries": mgr.fetch_retries,
                    "checksumFailures": mgr.checksum_failures,
                    "orphanedFiles": mgr.orphaned_files,
                    "speculativeDiscards": mgr.speculative_discards},
        "scheduler": _sched.stats.snapshot(),
        "degrade": degrade.counters(),
        "admission": _adm.stats.snapshot(),
        "sanitizer": _san.counters(),
        "device": _dm.counters(),
        "spill": {
            "orphanedFilesSwept":
                0 if cat is None
                else cat.metrics.get("orphaned_files_swept", 0),
            "deviceLostBuffers":
                0 if cat is None
                else cat.metrics.get("device_lost_buffers", 0)},
        "artifactsQuarantined":
            stats.snapshot()["artifactsQuarantined"],
        "semaphoreTimeouts": sem.get().timeouts,
    }


def unified_snapshot(session=None) -> dict:
    """The full observability surface as one nested dict: robustness
    counters, the compile ledger, spill-catalog + shuffle byte
    ledgers, per-session query metrics, and bus event counts."""
    from spark_rapids_tpu.obs import events as _events
    from spark_rapids_tpu.obs import telemetry as _telemetry
    from spark_rapids_tpu.runtime.compile_cache import stats
    from spark_rapids_tpu.shuffle.manager import get_shuffle_manager

    mgr = get_shuffle_manager()
    out = {
        "robustness": robustness_snapshot(),
        "compile": stats.snapshot(),
        "shuffle": {"bytesWritten": mgr.bytes_written,
                    "bytesInMemory": mgr.bytes_in_memory,
                    "blocksSpilled": mgr.blocks_spilled},
        "telemetry": _telemetry.ledger.registry_view(),
    }
    try:
        from spark_rapids_tpu.runtime.memory import _catalog

        if _catalog is not None:
            out["memory"] = dict(_catalog.metrics)
    except Exception:
        pass
    try:
        import sys

        srv = sys.modules.get("spark_rapids_tpu.serve.server")
        daemon = srv.active_daemon() if srv is not None else None
        if daemon is not None:
            st = daemon.status()
            out["serve"] = {
                "connections": len(st["connections"]),
                "inFlight": st["inFlight"],
                "queriesServed": st["queriesServed"],
                "planCache": st["planCache"],
                "tenants": st["tenants"],
            }
            if st.get("dedupe"):
                out["serve"]["dedupe"] = st["dedupe"]
    except Exception:
        pass
    try:
        import sys

        # fleet block: router + supervisor counters fold in when this
        # process hosts them (same sys.modules pattern as serve — no
        # import cost when the fleet layer never loaded), flattening
        # into the srtpu_fleet_* prom families
        fleet = {}
        rtr_mod = sys.modules.get("spark_rapids_tpu.serve.router")
        rtr = rtr_mod.active_router() if rtr_mod is not None else None
        if rtr is not None:
            fleet["router"] = rtr.stats_snapshot()
        sup_mod = sys.modules.get(
            "spark_rapids_tpu.serve.supervisor")
        sup = sup_mod.active_supervisor() if sup_mod is not None \
            else None
        if sup is not None:
            fleet["supervisor"] = sup.stats_snapshot()
        if fleet:
            out["fleet"] = fleet
    except Exception:
        pass
    bus = _events.get()
    if session is not None and getattr(session, "obs", None) is not None:
        bus = session.obs.bus or bus
    if bus is not None:
        out["events"] = dict(bus.counts)
    if session is not None:
        out["query"] = session.query_metrics.snapshot()
    return out


def flatten(d: dict, prefix: str = "",
            out: Optional[Dict[str, float]] = None) -> Dict[str, float]:
    """Nested counter dict -> flat {dotted.name: number}; non-numeric
    leaves drop."""
    if out is None:
        out = {}
    for k, v in d.items():
        name = f"{prefix}.{k}" if prefix else str(k)
        if isinstance(v, dict):
            flatten(v, name, out)
        elif isinstance(v, bool):
            out[name] = 1.0 if v else 0.0
        elif isinstance(v, (int, float)):
            out[name] = v
    return out
