"""Live metrics endpoint — the first piece of the service front-end.

A conf-gated (`spark.rapids.tpu.obs.http.{enabled,port}`) background
HTTP server exposing the session's observability surface to scrapers
and operators without any embedder glue:

- `GET /metrics`  -> the Prometheus text exposition `prom.render()`
  already produces (`session.prometheus_metrics()`), now actually
  scrape-able.
- `GET /queries`  -> JSON: the admission controller's live
  running/queued tables (runtime/admission.py `status()`) joined with
  the per-query data-movement summaries from the transfer ledger
  (obs/telemetry.py) and the recent HBM occupancy timeline.
- `GET /healthz`  -> `ok` (LIVENESS probe: the process is up and the
  endpoint thread is serving — always 200; a fenced or draining engine
  is alive, restarting it would only lose the warm state recovery is
  about to restore).
- `GET /readyz`   -> READINESS probe: 200 + JSON when the engine can
  accept new queries; 503 + the same JSON body (`ready`, `fenced`,
  `fencedChips`, `fencedHosts`, `draining`) while device-loss fencing
  (runtime/device_monitor.py) or an admission drain
  (runtime/admission.py begin_drain / serve/server.py) is in effect —
  load balancers stop ROUTING to the engine instead of killing it.

Lifecycle is session-owned (ObsManager): started at session init when
enabled, shut down leak-free in `close()` — the CI gate
(ci/telemetry_check.sh) asserts no lingering thread or socket. Binds
127.0.0.1 only: this is an operator/scrape surface, not an
authenticated public API. `port=0` binds an ephemeral port, reported
via `server.port` (and used by tests/CI to avoid collisions).
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional


class ObsHttpServer:
    """Daemon-thread HTTP server over the session's obs surface."""

    def __init__(self, session, port: int = 0, host: str = "127.0.0.1"):
        self._session = session
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *_a):  # no stderr chatter per scrape
                pass

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    code = 200
                    if path == "/metrics":
                        body = outer._metrics().encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    elif path == "/queries":
                        body = json.dumps(
                            outer._queries(), default=str).encode()
                        ctype = "application/json"
                    elif path == "/healthz":
                        body, ctype = b"ok\n", "text/plain"
                    elif path == "/readyz":
                        ready = outer._readiness()
                        body = json.dumps(ready).encode()
                        ctype = "application/json"
                        code = 200 if ready["ready"] else 503
                    else:
                        self.send_error(404, "unknown path")
                        return
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass  # client went away mid-response
                except Exception as e:
                    try:
                        self.send_error(500, type(e).__name__)
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="srtpu-obs-http", daemon=True)
        self._thread.start()

    # --- payload builders ---

    def _metrics(self) -> str:
        from spark_rapids_tpu.obs import prom

        return prom.render(self._session)

    def _queries(self) -> dict:
        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime import admission

        return {
            "admission": admission.get().status(),
            "queries": {
                str(qid): summary for qid, summary in
                telemetry.ledger.recent_query_summaries().items()},
            "hbmTimeline": telemetry.ledger.hbm_timeline(),
            "linkPeaks": telemetry.link_peaks(),
        }

    def _readiness(self) -> dict:
        from spark_rapids_tpu.runtime import admission, device_monitor

        mon = device_monitor.get()
        ctrl = admission.get()
        fenced = bool(mon.fenced)
        chips = sorted(device_monitor.fenced_chips())
        hosts = device_monitor.fenced_hosts()
        draining = bool(getattr(ctrl, "draining", False))
        # a fenced CHIP or HOST degrades capacity but the engine still
        # serves (survivor remesh / CPU rung) — only a process-wide
        # fence or a drain flips readiness. `load` is the admission
        # controller's shed signal (running/queued/queriesShed): the
        # fleet router reads it off this body to steer toward the
        # least-loaded replica
        return {"ready": not (fenced or draining),
                "fenced": fenced, "fencedChips": chips,
                "fencedHosts": hosts, "draining": draining,
                "load": ctrl.load()}

    # --- lifecycle ---

    def close(self) -> None:
        """Stop serving and release the socket + thread (idempotent)."""
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()          # stops serve_forever
        server.server_close()      # closes the listening socket
        self._thread.join(timeout=5.0)


class FleetHttpServer:
    """The ROUTER's health endpoint: /healthz is process liveness,
    /readyz aggregates member health — 200 while at least one replica
    is routable (the fleet can take a query), 503 when none is; the
    JSON body carries the per-replica table so an operator sees
    degraded-then-recovered capacity, not just a bit. /metrics renders
    the unified prom surface of the router process (srtpu_fleet_*)."""

    def __init__(self, router, port: int = 0,
                 host: str = "127.0.0.1"):
        self._router = router
        outer = self

        class _Handler(BaseHTTPRequestHandler):
            def log_message(self, *_a):
                pass

            def do_GET(self):
                try:
                    path = self.path.split("?", 1)[0]
                    code = 200
                    if path == "/healthz":
                        body, ctype = b"ok\n", "text/plain"
                    elif path == "/readyz":
                        snap = outer._router.health()
                        body = json.dumps(snap, default=str).encode()
                        ctype = "application/json"
                        code = 200 if snap["ready"] else 503
                    elif path == "/metrics":
                        from spark_rapids_tpu.obs import prom

                        body = prom.render(None).encode()
                        ctype = ("text/plain; version=0.0.4; "
                                 "charset=utf-8")
                    else:
                        self.send_error(404, "unknown path")
                        return
                    self.send_response(code)
                    self.send_header("Content-Type", ctype)
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                except BrokenPipeError:
                    pass
                except Exception as e:
                    try:
                        self.send_error(500, type(e).__name__)
                    except Exception:
                        pass

        self._server = ThreadingHTTPServer((host, int(port)), _Handler)
        self._server.daemon_threads = True
        self.port = self._server.server_address[1]
        self.host = host
        self._thread = threading.Thread(
            target=self._server.serve_forever,
            kwargs={"poll_interval": 0.1},
            name="srtpu-fleet-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        server, self._server = self._server, None
        if server is None:
            return
        server.shutdown()
        server.server_close()
        self._thread.join(timeout=5.0)


def maybe_start(session, conf=None) -> Optional[ObsHttpServer]:
    """Conf gate: an ObsHttpServer when obs.http.enabled, else None.
    A bind failure (port taken) degrades to a warning — observability
    must never fail a session."""
    from spark_rapids_tpu.config import rapids_conf as rc

    def get(entry):
        return conf.get(entry) if conf is not None else entry.default

    if not get(rc.OBS_HTTP_ENABLED):
        return None
    try:
        return ObsHttpServer(session, port=get(rc.OBS_HTTP_PORT))
    except OSError as e:
        import logging

        logging.getLogger(__name__).warning(
            "obs http endpoint failed to bind: %s", e)
        return None
