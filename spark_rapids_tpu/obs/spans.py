"""Query -> stage -> task -> operator span trees, built from the bus.

The reference attributes device work to plan nodes through NVTX ranges
read back in Nsight; the TPU engine's equivalent is this tree: every
scheduler attempt is a task span, every timed operator scope inside it
(PhysicalPlan.timed / profiler.annotate_with_metric) is an operator
span carrying wall + device nanoseconds, and losing speculative
attempts keep their spans marked `discarded` so double-counted time is
visible instead of silently folded in.

The builder is a plain bus subscriber; `build_from_events` replays a
recorded stream (obs/eventlog.py loader) through the SAME logic, which
is what makes a loaded log reconstruct the identical tree the live
session built.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Iterable, List, Optional


class Span:
    """One node of the tree. `kind` is query|stage|task|operator."""

    __slots__ = ("kind", "name", "query_id", "stage", "task", "attempt",
                 "speculative", "start_ts", "end_ts", "wall_ns",
                 "device_ns", "rows", "status", "children", "extra")

    def __init__(self, kind: str, name: str, query_id: int = 0,
                 stage: Optional[int] = None, task: Optional[int] = None,
                 attempt: Optional[int] = None, speculative: bool = False,
                 start_ts: Optional[float] = None):
        self.kind = kind
        self.name = name
        self.query_id = query_id
        self.stage = stage
        self.task = task
        self.attempt = attempt
        self.speculative = speculative
        self.start_ts = start_ts
        self.end_ts: Optional[float] = None
        self.wall_ns: int = 0
        self.device_ns: int = 0
        self.rows: Optional[int] = None
        self.status = "open"
        self.children: List["Span"] = []
        self.extra: Dict[str, object] = {}

    def to_dict(self) -> dict:
        d = {"kind": self.kind, "name": self.name,
             "queryId": self.query_id, "status": self.status,
             "startTs": self.start_ts, "endTs": self.end_ts,
             "wallNs": self.wall_ns, "deviceNs": self.device_ns,
             "rows": self.rows}
        if self.stage is not None:
            d["stage"] = self.stage
        if self.task is not None:
            d["task"] = self.task
        if self.attempt is not None:
            d["attempt"] = self.attempt
        if self.speculative:
            d["speculative"] = True
        if self.children:
            d["children"] = [c.to_dict() for c in self.children]
        return d

    def walk(self) -> Iterable["Span"]:
        yield self
        for c in self.children:
            yield from c.walk()

    def __repr__(self):
        return (f"Span({self.kind} {self.name!r} status={self.status} "
                f"children={len(self.children)})")


def tree_depth(root: Optional[Span]) -> int:
    if root is None:
        return 0
    return 1 + max((tree_depth(c) for c in root.children), default=0)


def operator_totals(root: Optional[Span],
                    include_discarded: bool = False) -> Dict[str, dict]:
    """Aggregate operator spans by operator name:
    {name: {wallNs, deviceNs, rows, count, discardedNs}}. Discarded
    (losing-attempt) spans contribute only to discardedNs unless
    `include_discarded`."""
    out: Dict[str, dict] = {}
    if root is None:
        return out
    for s in root.walk():
        if s.kind != "operator":
            continue
        t = out.setdefault(s.name, {"wallNs": 0, "deviceNs": 0,
                                    "rows": 0, "count": 0,
                                    "discardedNs": 0})
        if s.status == "discarded" and not include_discarded:
            t["discardedNs"] += s.wall_ns
            continue
        t["wallNs"] += s.wall_ns
        t["deviceNs"] += s.device_ns
        if s.rows:
            t["rows"] += s.rows
        t["count"] += 1
    return out


def task_rows(root: Optional[Span]) -> Optional[int]:
    """Committed result-stage row total (the query's output rows) when
    task attempt ends carried row counts."""
    if root is None:
        return None
    total, seen = 0, False
    for s in root.walk():
        if s.kind == "task" and s.status == "ok" and s.rows is not None \
                and s.extra.get("result_stage"):
            total += s.rows
            seen = True
    return total if seen else None


class _TreeState:
    def __init__(self, root: Span):
        self.root = root
        self.stages: Dict[int, Span] = {}
        self.tasks: Dict[tuple, Span] = {}


class SpanBuilder:
    """Bus subscriber incrementally building one tree per query.
    Thread-safe: the bus serializes delivery, but `build_from_events`
    and tests may drive it directly, so it keeps its own lock."""

    def __init__(self, on_complete: Optional[Callable[[Span], None]] = None,
                 keep: int = 4):
        self._on_complete = on_complete
        self._keep = max(1, keep)
        self._live: Dict[int, _TreeState] = {}
        self.completed: List[Span] = []
        self.last: Optional[Span] = None
        self._lock = threading.Lock()

    # --- subscriber entry ---

    def __call__(self, ev: dict) -> None:
        handler = getattr(self, "_on_" + ev["event"].replace(".", "_"),
                          None)
        if handler is None:
            return
        with self._lock:
            handler(ev)

    # --- per-event handlers (called under lock) ---

    def _state(self, ev: dict) -> Optional[_TreeState]:
        return self._live.get(ev.get("queryId") or 0)

    def _on_query_start(self, ev: dict) -> None:
        qid = ev.get("queryId") or 0
        root = Span("query", f"query-{qid}", qid, start_ts=ev["ts"])
        self._live[qid] = _TreeState(root)

    def _on_query_end(self, ev: dict) -> None:
        st = self._live.pop(ev.get("queryId") or 0, None)
        if st is None:
            return
        root = st.root
        root.end_ts = ev["ts"]
        root.status = ev.get("status", "ok")
        root.extra["engine"] = ev.get("engine")
        for s in root.walk():
            if s.status == "open":
                s.status = "unfinished"
        self.completed.append(root)
        del self.completed[:-self._keep]
        self.last = root
        if self._on_complete is not None:
            try:
                self._on_complete(root)
            except Exception:
                pass

    def _on_stage_start(self, ev: dict) -> None:
        st = self._state(ev)
        if st is None:
            return
        sp = Span("stage", str(ev.get("name", "stage")),
                  ev.get("queryId") or 0, stage=ev.get("stage"),
                  start_ts=ev["ts"])
        sp.extra["tasks"] = ev.get("tasks")
        st.stages[ev.get("stage")] = sp
        st.root.children.append(sp)

    def _on_stage_end(self, ev: dict) -> None:
        st = self._state(ev)
        if st is None:
            return
        sp = st.stages.get(ev.get("stage"))
        if sp is not None:
            sp.end_ts = ev["ts"]
            sp.status = ev.get("status", "ok")

    def _stage_for(self, st: _TreeState, ev: dict) -> Span:
        sid = ev.get("stage")
        sp = st.stages.get(sid)
        if sp is None:
            # task events may outrun their stage record on a replay
            # slice; synthesize a stage container rather than drop them
            sp = Span("stage", f"stage-{sid}", ev.get("queryId") or 0,
                      stage=sid, start_ts=ev["ts"])
            st.stages[sid] = sp
            st.root.children.append(sp)
        return sp

    def _on_task_attempt_start(self, ev: dict) -> None:
        st = self._state(ev)
        if st is None:
            return
        stage_sp = self._stage_for(st, ev)
        key = (ev.get("stage"), ev.get("task"), ev.get("attempt"))
        sp = Span("task",
                  f"{stage_sp.name}[{ev.get('task')}]#{ev.get('attempt')}",
                  ev.get("queryId") or 0, stage=ev.get("stage"),
                  task=ev.get("task"), attempt=ev.get("attempt"),
                  speculative=bool(ev.get("speculative")),
                  start_ts=ev["ts"])
        sp.extra["worker"] = ev.get("worker")
        if stage_sp.name == "result":
            sp.extra["result_stage"] = True
        st.tasks[key] = sp
        stage_sp.children.append(sp)

    def _on_task_attempt_end(self, ev: dict) -> None:
        st = self._state(ev)
        if st is None:
            return
        key = (ev.get("stage"), ev.get("task"), ev.get("attempt"))
        sp = st.tasks.get(key)
        if sp is None:
            return
        sp.end_ts = ev["ts"]
        sp.status = ev.get("status", "ok")
        if ev.get("wallMs") is not None:
            sp.wall_ns = int(ev["wallMs"] * 1_000_000)
        if ev.get("rows") is not None:
            sp.rows = ev["rows"]
        if sp.status != "ok":
            # a losing/failed attempt's operator work is non-result
            # work: mark the whole subtree so time attribution can
            # separate it (the speculation-accounting contract)
            for child in sp.children:
                for s in child.walk():
                    s.status = sp.status
        # accumulate device time upward for committed attempts
        elif sp.device_ns == 0:
            sp.device_ns = sum(c.device_ns for c in sp.children)

    def _on_operator_span(self, ev: dict) -> None:
        st = self._state(ev)
        if st is None:
            return
        key = (ev.get("stage"), ev.get("task"), ev.get("attempt"))
        parent = st.tasks.get(key) if ev.get("stage") is not None \
            else None
        sp = Span("operator", str(ev.get("operator")),
                  ev.get("queryId") or 0, stage=ev.get("stage"),
                  task=ev.get("task"), attempt=ev.get("attempt"),
                  speculative=bool(ev.get("speculative")),
                  start_ts=ev["ts"])
        sp.wall_ns = int(ev.get("wallNs") or 0)
        sp.device_ns = int(ev.get("deviceNs") or 0)
        sp.rows = ev.get("rows")
        sp.status = "ok"
        sp.extra["metric"] = ev.get("metric")
        (parent if parent is not None else st.root).children.append(sp)


def build_from_events(events: Iterable[dict]) -> List[Span]:
    """Replay a recorded event stream into finished span trees (one per
    query). Streams cut off before `query.end` still return their
    partial tree, marked `unfinished`."""
    done: List[Span] = []
    builder = SpanBuilder(on_complete=done.append, keep=1_000_000)
    for ev in events:
        builder(ev)
    for st in builder._live.values():
        root = st.root
        root.status = "unfinished"
        done.append(root)
    return done
