"""Qualification & profiling reports — the spark-rapids-tools analog.

The reference ships standalone qualification/profiling tools that read
Spark event logs and answer two questions: WHAT stayed on CPU (and
would the plugin help), and WHERE did the time go. Same surface here,
over the obs event stream: both reports run against a LIVE session
(its in-memory event history) or a SAVED event log path — the offline
workflow a fleet operator uses for regression triage.

- `qualification(source)`: every operator the planner kept on CPU,
  with the exact fallback reason the NOT_ON_TPU explain prints and an
  estimated share of query wall time attributed to it from the span
  tree.
- `profile(source)`: top-N operators by device time, shuffle/spill
  byte totals per tier, compile cache ratios, and
  retry/speculation/degradation/chaos counts.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Union

from spark_rapids_tpu.obs import spans as _spans

Source = Union[str, list, object]


def _events_from(source: Source) -> List[dict]:
    if isinstance(source, str):
        from spark_rapids_tpu.obs import eventlog

        return eventlog.load(source)
    if isinstance(source, list):
        return source
    obs = getattr(source, "obs", None)
    if obs is not None and obs.history is not None:
        return obs.history.events()
    raise TypeError(
        "report source must be an event-log path, a list of events, or "
        "a session with observability enabled "
        "(spark.rapids.tpu.obs.enabled)")


def _last_query(events: List[dict]) -> List[dict]:
    qids = [e["queryId"] for e in events if e.get("queryId")]
    if not qids:
        return []
    last = qids[-1]
    return [e for e in events if e.get("queryId") == last]


def _tree_for(events: List[dict]) -> Optional[_spans.Span]:
    trees = _spans.build_from_events(events)
    return trees[-1] if trees else None


def _fallback_share(node: str, totals: Dict[str, dict],
                    total_wall: int) -> Optional[float]:
    """Wall-time share of the CPU exec(s) implementing a logical node:
    placement events carry LOGICAL names (Filter), spans carry physical
    exec names (CpuFilterExec) — match on the embedded logical name."""
    if total_wall <= 0:
        return None
    wall = sum(t["wallNs"] for name, t in totals.items()
               if name.startswith("Cpu") and node in name)
    if wall == 0:
        return None
    return wall / total_wall


# ---------------------------------------------------------- qualification

def qualification_data(source: Source) -> List[dict]:
    """Rows for every planner CPU fallback of the (last) query:
    [{node, depth, reason, timeShare}]. `reason` is verbatim the
    '; '-joined string explain_potential_tpu_plan(mode='NOT_ON_TPU')
    prints for that node."""
    events = _last_query(_events_from(source))
    tree = _tree_for(events)
    totals = _spans.operator_totals(tree)
    total_wall = sum(t["wallNs"] for t in totals.values())
    rows = []
    for ev in events:
        if ev["event"] != "plan.placement" or ev.get("onDevice"):
            continue
        rows.append({
            "node": ev["node"],
            "depth": ev.get("depth", 0),
            "reason": ev.get("reason") or "",
            "timeShare": _fallback_share(ev["node"], totals, total_wall),
        })
    return rows


def qualification(source: Source) -> str:
    """Human-readable qualification report (CPU-fallback inventory)."""
    rows = qualification_data(source)
    if not rows:
        return ("== TPU qualification ==\n"
                "(every planned operator runs on device)")
    lines = ["== TPU qualification ==",
             f"{len(rows)} operator(s) kept on CPU:"]
    for r in rows:
        share = ("  ~" + f"{100.0 * r['timeShare']:.1f}% of query time"
                 if r["timeShare"] is not None else "")
        lines.append(f"  {'  ' * r['depth']}{r['node']}: "
                     f"{r['reason']}{share}")
    return "\n".join(lines)


# ---------------------------------------------------------------- profile

def profile_data(source: Source, top_n: int = 10) -> dict:
    """Structured profile of the (last) query in `source`. Sanitizer
    verdicts are the exception to last-query scoping: a wait-for cycle
    spans queries by construction (and the retried victim finishes
    LAST), so the audit section aggregates over the whole source."""
    all_events = _events_from(source)
    events = _last_query(all_events)
    tree = _tree_for(events)
    totals = _spans.operator_totals(tree)
    top = sorted(totals.items(), key=lambda kv: -kv[1]["deviceNs"])
    counts: Dict[str, int] = {}
    shuffle = {"bytesWritten": 0, "bytesFetched": 0, "writes": 0,
               "fetches": 0, "retries": 0}
    spill = {"toHostBytes": 0, "toDiskBytes": 0, "unspillBytes": 0}
    compile_c = {"miss": 0, "hit": 0, "warm": 0, "quarantine": 0}
    recovery = {"attempts": 0, "retried": 0, "speculated": 0,
                "discarded": 0, "lost": 0, "failed": 0,
                "degradations": 0, "chaosInjections": 0}
    movement: Dict[str, Dict[str, int]] = {}
    sanitizer = {"deadlocks": 0, "inversions": 0, "victims": 0,
                 "lastCycle": None}
    telemetry_summary = None
    for ev in events:
        et = ev["event"]
        counts[et] = counts.get(et, 0) + 1
        if et == "shuffle.write":
            shuffle["writes"] += 1
            shuffle["bytesWritten"] += ev.get("bytes") or 0
        elif et == "shuffle.fetch":
            shuffle["fetches"] += 1
            shuffle["bytesFetched"] += ev.get("bytes") or 0
        elif et == "shuffle.retry":
            shuffle["retries"] += 1
        elif et == "spill":
            b = ev.get("bytes") or 0
            if ev.get("direction") == "up":
                spill["unspillBytes"] += b
            elif ev.get("toTier") == "HOST":
                spill["toHostBytes"] += b
            else:
                spill["toDiskBytes"] += b
        elif et == "compile":
            kind = ev.get("kind", "miss")
            compile_c[kind] = compile_c.get(kind, 0) + 1
        elif et == "task.attempt.start":
            recovery["attempts"] += 1
            if ev.get("speculative"):
                recovery["speculated"] += 1
        elif et == "task.attempt.end":
            status = ev.get("status")
            if status in ("discarded", "lost", "failed"):
                recovery[status] = recovery.get(status, 0) + 1
            if status == "lost":
                recovery["retried"] += 1
        elif et == "degrade":
            recovery["degradations"] += 1
        elif et == "chaos":
            recovery["chaosInjections"] += 1
        elif et == "transfer":
            d = movement.setdefault(str(ev.get("direction")),
                                    {"bytes": 0, "count": 0})
            d["bytes"] += ev.get("bytes") or 0
            d["count"] += 1
        elif et == "telemetry.summary":
            # end-of-query roofline record (the last one wins: nested
            # collects never emit it, so there is exactly one per query)
            telemetry_summary = {
                k: ev.get(k) for k in
                ("bytesMoved", "bytesMovedTotal", "hbmPeakBytes",
                 "rooflineFrac", "linkFrac", "bytesPerOutputRow",
                 "wallMs") if ev.get(k) is not None}
    for ev in all_events:
        et = ev["event"]
        if et == "sanitizer.deadlock":
            sanitizer["deadlocks"] += 1
            if ev.get("victim") is not None:
                sanitizer["victims"] += 1
            sanitizer["lastCycle"] = ev.get("cycle")
        elif et == "sanitizer.inversion":
            sanitizer["inversions"] += 1
    served = compile_c["hit"] + compile_c["warm"]
    requests = served + compile_c["miss"]
    return {
        "queryId": events[-1]["queryId"] if events else None,
        "eventCounts": counts,
        "spanTreeDepth": _spans.tree_depth(tree),
        "topOperators": [
            {"operator": name, **t} for name, t in top[:top_n]],
        "outputRows": _spans.task_rows(tree),
        "shuffle": shuffle,
        "spill": spill,
        "compile": {**compile_c,
                    "cacheServedRatio": (served / requests
                                         if requests else None)},
        "recovery": recovery,
        "sanitizer": sanitizer,
        "dataMovement": movement,
        "telemetry": telemetry_summary,
    }


def profile(source: Source, top_n: int = 10) -> str:
    """Human-readable profile report."""
    d = profile_data(source, top_n)
    lines = ["== TPU profile ==",
             f"query {d['queryId']}; span tree depth "
             f"{d['spanTreeDepth']}; output rows {d['outputRows']}"]
    lines.append(f"top operators by device time (of "
                 f"{len(d['topOperators'])} shown):")
    for t in d["topOperators"]:
        lines.append(
            f"  {t['operator']}: device {t['deviceNs'] / 1e6:.2f} ms, "
            f"wall {t['wallNs'] / 1e6:.2f} ms, calls {t['count']}"
            + (f", rows {t['rows']}" if t["rows"] else "")
            + (f", discarded {t['discardedNs'] / 1e6:.2f} ms"
               if t["discardedNs"] else ""))
    sh, sp = d["shuffle"], d["spill"]
    lines.append(f"shuffle: {sh['bytesWritten']} B written over "
                 f"{sh['writes']} block(s), {sh['bytesFetched']} B "
                 f"fetched, {sh['retries']} retrie(s)")
    lines.append(f"spill: {sp['toHostBytes']} B to host, "
                 f"{sp['toDiskBytes']} B to disk, "
                 f"{sp['unspillBytes']} B unspilled")
    c = d["compile"]
    ratio = ("n/a" if c["cacheServedRatio"] is None
             else f"{100.0 * c['cacheServedRatio']:.0f}%")
    lines.append(f"compile: {c['miss']} compiled, {c['hit']} cache "
                 f"hit(s), {c['warm']} warm, cache-served {ratio}")
    r = d["recovery"]
    lines.append(f"recovery: {r['attempts']} attempt(s), "
                 f"{r['retried']} retried, {r['speculated']} "
                 f"speculated, {r['discarded']} discarded, "
                 f"{r['degradations']} degradation(s), "
                 f"{r['chaosInjections']} chaos injection(s)")
    sz = d["sanitizer"]
    if sz["deadlocks"] or sz["inversions"]:
        lines.append(
            f"sanitizer: {sz['deadlocks']} deadlock cycle(s) "
            f"detected, {sz['victims']} victim(s) unwound, "
            f"{sz['inversions']} order inversion(s)")
        if sz["lastCycle"]:
            rows = "; ".join(
                f"query {r['queryId']} waits on {r['waitsOn']}"
                for r in sz["lastCycle"])
            lines.append(f"  last cycle: {rows}")
    if d["dataMovement"]:
        parts = [f"{dd} {v['bytes']} B/{v['count']} transfer(s)"
                 for dd, v in sorted(d["dataMovement"].items())]
        lines.append("data movement: " + ", ".join(parts))
    tel = d.get("telemetry")
    if tel:
        rf = tel.get("rooflineFrac")
        bpr = tel.get("bytesPerOutputRow")
        lines.append(
            f"roofline: {tel.get('bytesMovedTotal', 0)} B moved, "
            f"hbm peak {tel.get('hbmPeakBytes', 0)} B"
            + (f", roofline_frac {rf}" if rf is not None else "")
            + (f", {bpr} B/output row" if bpr is not None else ""))
    return "\n".join(lines)
