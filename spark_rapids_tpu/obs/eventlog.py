"""JSONL event log — the Spark event-log analog.

A bus subscriber (conf `spark.rapids.tpu.eventLog.{enabled,dir}`)
writes every query's event stream to its own JSONL file under the log
directory: opened as `eventlog-q<N>-p1.jsonl.inprogress` at
`query.start`, rolled to new part files past
`eventLog.rotation.maxBytes`, and ATOMICALLY finalized (all parts
renamed off `.inprogress`) when `query.end` lands — a crashed process
leaves `.inprogress` files, never a truncated finalized log.

The writer keeps ONE OPEN STREAM PER QUERY, keyed by the event's
`queryId`: concurrent tenants (admission allows several running
queries, PR 5) interleave on the bus but land in fully isolated
per-query files — query A's `query.end` finalizes only A's parts while
B keeps writing. Events outside any query scope (queryId 0) drop.

`load()` reads a finalized file, a query's parts, or a whole directory
back into the event stream (validating the schema envelope per line),
and `load_spans()` replays it through the same SpanBuilder the live
session uses — which is why a loaded log reconstructs the IDENTICAL
span tree (the qualification/profiling tools' offline entry point).
"""

from __future__ import annotations

import json
import os
import re
import threading
from typing import Dict, List, Optional

from spark_rapids_tpu.obs import events as _events
from spark_rapids_tpu.obs import spans as _spans

_FINAL_RE = re.compile(r"^eventlog-q(\d+)-p(\d+)\.jsonl$")
_INPROGRESS_SUFFIX = ".inprogress"


class EventLogError(RuntimeError):
    pass


def default_dir() -> str:
    import tempfile

    return os.path.join(tempfile.gettempdir(), "srtpu_eventlog")


class _QueryStream:
    """One query's open log: file handle, part counter, pending paths."""

    __slots__ = ("qid", "f", "part", "bytes", "open_paths")

    def __init__(self, qid: int):
        self.qid = qid
        self.f = None
        self.part = 0
        self.bytes = 0
        self.open_paths: List[str] = []


class EventLogWriter:
    """Per-query JSONL writer with rotation + atomic finalize; keeps
    one independent stream per in-flight queryId so concurrent tenants
    get isolated logs."""

    def __init__(self, log_dir: str, rotate_bytes: int = 64 << 20):
        self.dir = log_dir or default_dir()
        self.rotate_bytes = max(4096, int(rotate_bytes))
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        self._streams: Dict[int, _QueryStream] = {}
        self.files_written = 0
        self.events_written = 0
        self.write_errors = 0

    # --- subscriber entry ---

    def __call__(self, ev: dict) -> None:
        with self._lock:
            try:
                qid = ev.get("queryId") or 0
                if ev["event"] == "query.start":
                    # a duplicate start for an open qid (replayed
                    # stream): finalize the orphan first
                    self._finalize_locked(self._streams.pop(qid, None))
                    if not qid:
                        return  # scope-less stream: nothing to key on
                    st = self._streams[qid] = _QueryStream(qid)
                    self._roll_locked(st)
                st = self._streams.get(qid)
                if st is None:
                    return  # events outside any open query scope drop
                line = json.dumps(ev, separators=(",", ":"),
                                  sort_keys=True)
                st.f.write(line + "\n")
                st.bytes += len(line) + 1
                self.events_written += 1
                if ev["event"] == "query.end":
                    self._finalize_locked(self._streams.pop(qid, None))
                elif st.bytes >= self.rotate_bytes:
                    self._roll_locked(st)
            except Exception:
                self.write_errors += 1

    # --- file lifecycle (under lock) ---

    def _inprogress(self, qid: int, part: int) -> str:
        return os.path.join(
            self.dir,
            f"eventlog-q{qid}-p{part}.jsonl{_INPROGRESS_SUFFIX}")

    def _roll_locked(self, st: _QueryStream) -> None:
        if st.f is not None:
            st.f.flush()
            st.f.close()
        st.part += 1
        st.bytes = 0
        path = self._inprogress(st.qid, st.part)
        st.f = open(path, "w")
        st.open_paths.append(path)

    def _finalize_locked(self, st: Optional[_QueryStream]) -> None:
        if st is None or st.f is None:
            return
        st.f.flush()
        st.f.close()
        st.f = None
        for p in st.open_paths:
            final = p[:-len(_INPROGRESS_SUFFIX)]
            try:
                os.replace(p, final)  # atomic publish
                self.files_written += 1
            except OSError:
                self.write_errors += 1
        st.open_paths = []

    def open_query_ids(self) -> List[int]:
        with self._lock:
            return sorted(self._streams)

    def close(self) -> None:
        """Session stop: finalize every open (crashed-query) log so
        its events survive; a file still finalizes without a query.end
        line (the loader marks its tree `unfinished`)."""
        with self._lock:
            for qid in list(self._streams):
                self._finalize_locked(self._streams.pop(qid))


# ----------------------------------------------------------- validation

def validate_event(ev: dict) -> List[str]:
    """Schema check for one event object; returns error strings."""
    errs = []
    for k in _events.REQUIRED_KEYS:
        if k not in ev:
            errs.append(f"missing required key {k!r}")
    v = ev.get("schemaVersion")
    if v is not None and v != _events.SCHEMA_VERSION:
        errs.append(f"schemaVersion {v} != {_events.SCHEMA_VERSION}")
    et = ev.get("event")
    if et is not None and et not in _events.EVENT_TYPES:
        errs.append(f"unknown event type {et!r}")
    return errs


# -------------------------------------------------------------- loading

def _load_file(path: str, strict: bool) -> List[dict]:
    out = []
    with open(path) as f:
        for i, line in enumerate(f, 1):
            line = line.strip()
            if not line:
                continue
            try:
                ev = json.loads(line)
            except ValueError as e:
                raise EventLogError(f"{path}:{i}: bad JSON: {e}")
            errs = validate_event(ev)
            if errs and strict:
                raise EventLogError(f"{path}:{i}: {'; '.join(errs)}")
            out.append(ev)
    return out


def log_files(log_dir: str, query_id: Optional[int] = None) -> List[str]:
    """Finalized log files under a directory, in (query, part) order."""
    found = []
    for name in os.listdir(log_dir):
        m = _FINAL_RE.match(name)
        if m and (query_id is None or int(m.group(1)) == query_id):
            found.append((int(m.group(1)), int(m.group(2)), name))
    return [os.path.join(log_dir, n) for _q, _p, n in sorted(found)]


def load(path: str, query_id: Optional[int] = None,
         strict: bool = True) -> List[dict]:
    """Read an event stream back: `path` is a finalized log file or a
    log directory (optionally narrowed to one query). Events return in
    write order (parts concatenate in sequence)."""
    if os.path.isdir(path):
        files = log_files(path, query_id)
        if not files:
            raise EventLogError(
                f"no finalized event logs under {path!r}"
                + (f" for query {query_id}" if query_id else ""))
    else:
        files = [path]
    out: List[dict] = []
    for p in files:
        out.extend(_load_file(p, strict))
    return out


def load_spans(path: str, query_id: Optional[int] = None
               ) -> List["_spans.Span"]:
    """Reconstruct span trees from a saved log — same builder as the
    live session, so the trees are identical to what it held."""
    return _spans.build_from_events(load(path, query_id))
