"""Prometheus text-exposition dump of the unified metric registry.

One call renders every engine counter — robustness, compile ledger,
shuffle/spill bytes, per-session query metrics, bus event counts — in
the text format a Prometheus scrape (or a pushgateway hook) ingests:

    srtpu_robustness_scheduler_tasksLaunched 42
    srtpu_events_total{event="operator.span"} 118

The engine has no HTTP server; embedders expose `render()` behind
whatever endpoint their deployment runs (the dashboards goal of the
ROADMAP north star). Everything is emitted as gauges: most values are
monotonic in practice, but cross-session resets (new shuffle manager,
reconfigured registries) would violate Prometheus counter semantics.
"""

from __future__ import annotations

import re
from typing import Dict

from spark_rapids_tpu.obs import registry as _registry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
PREFIX = "srtpu"


def _metric_name(dotted: str) -> str:
    return f"{PREFIX}_{_NAME_RE.sub('_', dotted)}"


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def render(session=None) -> str:
    """The full unified registry as Prometheus text exposition."""
    snap = _registry.unified_snapshot(session)
    # labeled families: per-event and per-chaos-site counts read better
    # as one family with a label than as N families
    events = snap.pop("events", {})
    chaos = snap.get("robustness", {}).pop("chaos", {})
    lines = []
    flat: Dict[str, float] = _registry.flatten(snap)
    for name in sorted(flat):
        mname = _metric_name(name)
        lines.append(f"# TYPE {mname} gauge")
        lines.append(f"{mname} {_fmt_value(flat[name])}")
    if events:
        mname = f"{PREFIX}_events_total"
        lines.append(f"# TYPE {mname} gauge")
        for ev in sorted(events):
            lines.append(f'{mname}{{event="{ev}"}} '
                         f"{_fmt_value(events[ev])}")
    if chaos:
        for field in ("checked", "injected"):
            mname = f"{PREFIX}_chaos_{field}_total"
            lines.append(f"# TYPE {mname} gauge")
            for site in sorted(chaos):
                lines.append(
                    f'{mname}{{site="{site}"}} '
                    f"{_fmt_value(chaos[site].get(field, 0))}")
    return "\n".join(lines) + "\n"
