"""Prometheus text-exposition dump of the unified metric registry.

One call renders every engine counter — robustness, compile ledger,
shuffle/spill bytes, data-movement telemetry, per-session query
metrics, bus event counts — in the text format a Prometheus scrape (or
a pushgateway hook) ingests:

    srtpu_robustness_scheduler_tasksLaunched 42
    srtpu_events_total{event="operator.span"} 118
    srtpu_transfer_bytes_total{direction="h2d",site="scan.upload"} 9e6
    srtpu_query_bytes_moved{queryId="7",direction="d2h"} 1024

Label VALUES are escaped per the exposition-format rules (backslash,
double-quote, newline) — queryIds and operator/site names flow in from
user-visible strings and must never produce unparseable text. The
engine's own HTTP endpoint (obs/http.py, conf
`spark.rapids.tpu.obs.http.enabled`) serves `render()` at `/metrics`;
embedders can also expose it behind their own server. Everything is
emitted as gauges: most values are monotonic in practice, but
cross-session resets (new shuffle manager, reconfigured registries)
would violate Prometheus counter semantics.
"""

from __future__ import annotations

import re
from typing import Dict

from spark_rapids_tpu.obs import registry as _registry

_NAME_RE = re.compile(r"[^a-zA-Z0-9_]")
PREFIX = "srtpu"


def _metric_name(dotted: str) -> str:
    return f"{PREFIX}_{_NAME_RE.sub('_', dotted)}"


def escape_label(v) -> str:
    """Escape one label VALUE per the Prometheus text exposition
    format: backslash first, then double-quote and newline."""
    return (str(v).replace("\\", r"\\").replace('"', r"\"")
            .replace("\n", r"\n"))


def _labels(**kv) -> str:
    return ",".join(f'{k}="{escape_label(v)}"' for k, v in kv.items())


def _fmt_value(v: float) -> str:
    if isinstance(v, float) and not v.is_integer():
        return repr(v)
    return str(int(v))


def render(session=None) -> str:
    """The full unified registry as Prometheus text exposition."""
    snap = _registry.unified_snapshot(session)
    # labeled families: per-event, per-chaos-site, per-transfer-site
    # and per-query counts read better as one family with labels than
    # as N families
    events = snap.pop("events", {})
    chaos = snap.get("robustness", {}).pop("chaos", {})
    snap.pop("telemetry", {})  # re-rendered as labeled families below
    lines = []
    flat: Dict[str, float] = _registry.flatten(snap)
    for name in sorted(flat):
        mname = _metric_name(name)
        lines.append(f"# TYPE {mname} gauge")
        lines.append(f"{mname} {_fmt_value(flat[name])}")
    if events:
        mname = f"{PREFIX}_events_total"
        lines.append(f"# TYPE {mname} gauge")
        for ev in sorted(events):
            lines.append(f"{mname}{{{_labels(event=ev)}}} "
                         f"{_fmt_value(events[ev])}")
    if chaos:
        for field in ("checked", "injected"):
            mname = f"{PREFIX}_chaos_{field}_total"
            lines.append(f"# TYPE {mname} gauge")
            for site in sorted(chaos):
                lines.append(
                    f"{mname}{{{_labels(site=site)}}} "
                    f"{_fmt_value(chaos[site].get(field, 0))}")
    # sanitizer counters as first-class *_total families (they also
    # appear under srtpu_robustness_sanitizer_* via the flatten above;
    # these are the stable names dashboards alert on)
    from spark_rapids_tpu.runtime import sanitizer as _san

    for field, value in sorted(_san.counters().items()):
        if field == "enabled":
            continue
        mname = f"{PREFIX}_sanitizer_{field}_total"
        lines.append(f"# TYPE {mname} gauge")
        lines.append(f"{mname} {_fmt_value(value)}")
    lines.extend(_telemetry_lines())
    return "\n".join(lines) + "\n"


def _telemetry_lines() -> list:
    """Data-movement families: process totals per (direction, site),
    HBM occupancy gauges, and the retained per-query summaries —
    per-query bytes_moved/hbm_peak/roofline_frac straight off a
    /metrics scrape."""
    from spark_rapids_tpu.obs import telemetry as _tel

    lines = []
    rows = _tel.ledger.site_rows()
    if rows:
        for field, unit in (("bytes", "bytes"), ("count", "count")):
            mname = f"{PREFIX}_transfer_{unit}_total"
            lines.append(f"# TYPE {mname} gauge")
            for r in rows:
                lines.append(
                    f"{mname}{{{_labels(direction=r['direction'], site=r['site'])}}} "
                    f"{_fmt_value(r[field])}")
    view = _tel.ledger.registry_view()
    for k, v in sorted(view["hbm"].items()):
        mname = f"{PREFIX}_hbm_{k}"
        lines.append(f"# TYPE {mname} gauge")
        lines.append(f"{mname} {_fmt_value(v)}")
    # commit-protocol write families (io/commit.py process totals):
    # jobs/files/bytes/rows published, cumulative job-commit wall
    # time, aborts and lakehouse optimistic-commit conflicts
    from spark_rapids_tpu.io import commit as _iocommit

    wt = _iocommit.write_totals()
    if wt.get("jobs") or wt.get("aborts") or wt.get("conflicts"):
        _wname = {"commitMs": "commit_ms"}
        for k in sorted(wt):
            mname = f"{PREFIX}_write_{_wname.get(k, k)}_total"
            lines.append(f"# TYPE {mname} gauge")
            lines.append(f"{mname} {_fmt_value(wt[k])}")
    summaries = _tel.ledger.recent_query_summaries()
    if summaries:
        families: dict = {f"{PREFIX}_query_bytes_moved": [],
                          f"{PREFIX}_query_hbm_peak_bytes": [],
                          f"{PREFIX}_query_roofline_frac": [],
                          f"{PREFIX}_query_stream_window_peak_bytes": [],
                          f"{PREFIX}_query_stream_partitions": [],
                          f"{PREFIX}_query_stream_overlap_frac": [],
                          f"{PREFIX}_query_write_bytes": [],
                          f"{PREFIX}_query_write_files": [],
                          f"{PREFIX}_query_write_commit_ms": []}
        for qid, s in summaries.items():
            for d, b in s.get("bytesMoved", {}).items():
                families[f"{PREFIX}_query_bytes_moved"].append(
                    ({"queryId": qid, "direction": d}, b))
            families[f"{PREFIX}_query_hbm_peak_bytes"].append(
                ({"queryId": qid}, s.get("hbmPeakBytes", 0)))
            if s.get("rooflineFrac") is not None:
                families[f"{PREFIX}_query_roofline_frac"].append(
                    ({"queryId": qid}, s["rooflineFrac"]))
            # streaming-executor families (stream/executor.py): only
            # queries that ran the out-of-core rung carry them
            if s.get("partitionsStreamed"):
                families[
                    f"{PREFIX}_query_stream_window_peak_bytes"].append(
                    ({"queryId": qid}, s.get("windowPeakBytes", 0)))
                families[f"{PREFIX}_query_stream_partitions"].append(
                    ({"queryId": qid}, s["partitionsStreamed"]))
            if s.get("overlapFraction") is not None:
                families[f"{PREFIX}_query_stream_overlap_frac"].append(
                    ({"queryId": qid}, s["overlapFraction"]))
            # write block (io/commit.py): queries that published output
            w = s.get("write")
            if w:
                families[f"{PREFIX}_query_write_bytes"].append(
                    ({"queryId": qid}, w.get("bytes", 0)))
                families[f"{PREFIX}_query_write_files"].append(
                    ({"queryId": qid}, w.get("files", 0)))
                families[f"{PREFIX}_query_write_commit_ms"].append(
                    ({"queryId": qid}, w.get("commitMs", 0)))
        for mname, samples in families.items():
            if not samples:
                continue
            lines.append(f"# TYPE {mname} gauge")
            for labels, value in samples:
                lines.append(f"{mname}{{{_labels(**labels)}}} "
                             f"{_fmt_value(value)}")
    return lines
