"""Observability subsystem: event bus, span trees, event log, reports.

Layout (see docs/observability.md):

- `events.py`   typed thread-safe event bus + query/task context
- `spans.py`    query->stage->task->operator span trees from the bus
- `eventlog.py` conf-gated JSONL event log (rotation, atomic finalize)
                + loader reconstructing span trees offline
- `report.py`   qualification + profile reports (live session or log)
- `prom.py`     Prometheus text-exposition dump
- `registry.py` unified views over every engine counter

The session owns one `ObsManager` (api/session.py): it wires the bus,
the span builder, the in-memory history and the optional event-log
writer, and installs the bus as the process emit target that every
runtime module's `events.emit(...)` hooks feed.
"""

from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.obs import events as events  # noqa: F401
from spark_rapids_tpu.obs.events import EventBus, EventHistory
from spark_rapids_tpu.obs.spans import Span, SpanBuilder


class ObsManager:
    """Session-scoped observability wiring (created in
    TpuSparkSession.__init__, closed in stop())."""

    def __init__(self, conf=None):
        from spark_rapids_tpu.config import rapids_conf as rc

        def get(entry):
            return conf.get(entry) if conf is not None else entry.default

        self.enabled = bool(get(rc.OBS_ENABLED))
        self.bus: Optional[EventBus] = None
        self.history: Optional[EventHistory] = None
        self.spans: Optional[SpanBuilder] = None
        self.writer = None
        if not self.enabled:
            return
        self.bus = EventBus()
        self.history = EventHistory(get(rc.OBS_HISTORY_EVENTS))
        self.spans = SpanBuilder()
        self.bus.subscribe(self.history)
        self.bus.subscribe(self.spans)
        if get(rc.EVENTLOG_ENABLED):
            from spark_rapids_tpu.obs.eventlog import EventLogWriter

            self.writer = EventLogWriter(
                get(rc.EVENTLOG_DIR),
                rotate_bytes=get(rc.EVENTLOG_ROTATE_BYTES))
            self.bus.subscribe(self.writer)
        events.install(self.bus)

    @property
    def last_spans(self) -> Optional[Span]:
        """Span tree of the most recently completed query."""
        return self.spans.last if self.spans is not None else None

    def query_events(self, query_id: Optional[int] = None) -> List[dict]:
        if self.history is None:
            return []
        if query_id is None:
            query_id = self.history.last_query_id()
        return self.history.events(query_id)

    def close(self) -> None:
        if self.writer is not None:
            self.writer.close()
        if self.bus is not None:
            events.uninstall(self.bus)
