"""Observability subsystem: event bus, span trees, event log, reports.

Layout (see docs/observability.md):

- `events.py`    typed thread-safe event bus + query/task context
- `spans.py`     query->stage->task->operator span trees from the bus
- `eventlog.py`  conf-gated JSONL event log (per-query files, rotation,
                 atomic finalize) + loader reconstructing span trees
- `telemetry.py` data-movement transfer ledger, HBM occupancy timeline,
                 roofline accounting (per-query bytesMoved/hbmPeak/
                 rooflineFrac)
- `report.py`    qualification + profile reports (live session or log)
- `prom.py`      Prometheus text-exposition dump
- `http.py`      conf-gated live scrape endpoint (/metrics, /queries)
- `registry.py`  unified views over every engine counter

The session owns one `ObsManager` (api/session.py): it wires the bus,
the span builder, the in-memory history and the optional event-log
writer, and installs the bus as the process emit target that every
runtime module's `events.emit(...)` hooks feed.
"""

from __future__ import annotations

from typing import List, Optional

from spark_rapids_tpu.obs import events as events  # noqa: F401
from spark_rapids_tpu.obs.events import EventBus, EventHistory
from spark_rapids_tpu.obs.spans import Span, SpanBuilder


class ObsManager:
    """Session-scoped observability wiring (created in
    TpuSparkSession.__init__, closed in stop())."""

    def __init__(self, conf=None):
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.obs import telemetry

        def get(entry):
            return conf.get(entry) if conf is not None else entry.default

        # the transfer ledger is bus-independent: it keeps counting
        # with obs.enabled=false (its own conf gates it)
        telemetry.configure(conf)
        self.enabled = bool(get(rc.OBS_ENABLED))
        self.bus: Optional[EventBus] = None
        self.history: Optional[EventHistory] = None
        self.spans: Optional[SpanBuilder] = None
        self.writer = None
        self.http = None
        if not self.enabled:
            return
        self.bus = EventBus()
        self.history = EventHistory(get(rc.OBS_HISTORY_EVENTS))
        self.spans = SpanBuilder()
        self.bus.subscribe(self.history)
        self.bus.subscribe(self.spans)
        if get(rc.EVENTLOG_ENABLED):
            from spark_rapids_tpu.obs.eventlog import EventLogWriter

            self.writer = EventLogWriter(
                get(rc.EVENTLOG_DIR),
                rotate_bytes=get(rc.EVENTLOG_ROTATE_BYTES))
            self.bus.subscribe(self.writer)
        events.install(self.bus)

    def start_http(self, session, conf=None) -> None:
        """Bring up the conf-gated live scrape endpoint (obs/http.py).
        Independent of obs.enabled: the Prometheus dump renders plain
        process counters even with the bus off."""
        from spark_rapids_tpu.obs import http as obs_http

        self.http = obs_http.maybe_start(session, conf)

    @property
    def last_spans(self) -> Optional[Span]:
        """Span tree of the most recently completed query."""
        return self.spans.last if self.spans is not None else None

    def query_events(self, query_id: Optional[int] = None) -> List[dict]:
        if self.history is None:
            return []
        if query_id is None:
            query_id = self.history.last_query_id()
        return self.history.events(query_id)

    def close(self) -> None:
        if self.http is not None:
            try:
                self.http.close()
            except Exception:
                pass
            self.http = None
        if self.writer is not None:
            self.writer.close()
        if self.bus is not None:
            events.uninstall(self.bus)
