"""Data-movement telemetry: transfer ledger, HBM occupancy, roofline.

BENCH_r05 measured roofline_frac ~ 0.006 over a 0.11 GB/s host->device
link — and every planned optimization (ICI-resident shuffle, compressed
execution, out-of-core streaming) is a bytes-moved optimization. The
reference stack's profiling tool attributes transfer volume per
operator to drive exactly that tuning loop; this module is the engine's
equivalent measurement substrate:

- **Transfer ledger**: every byte-crossing site (H2D uploads, D2H
  materialization at collect, shuffle write/fetch, disk spill/unspill)
  calls `record(direction, site, bytes, ns)`; entries are attributed to
  the owning query through the obs query/task scope (obs/events.py) and
  mirrored onto the event bus as `transfer` events so the event log is
  a complete audit of data movement. Directions are the four physical
  channels: `h2d`, `d2h`, `spill-disk` (disk I/O of the spill tiers),
  and `shuffle` (inter-task/inter-process block movement).

- **HBM occupancy timeline**: the SpillCatalog's reservation ledger
  (runtime/memory.py) feeds `hbm_global` / `hbm_query` on every device
  reserve/release, so the process keeps a bounded (ts, reservedBytes)
  timeline, a global high-water mark that tracks the pool's own peak,
  and a per-query device-footprint peak — a query's peak HBM usage is
  a reported number, not a guess. Spill pressure (synchronous spills
  triggered by a failed reservation) is counted per query.

- **Roofline accounting**: `link_peaks()` measures the H2D/D2H link
  once per process (a timed `device_put`/`device_get` of a fixed
  buffer) and reads the device HBM peak bandwidth from the public spec
  table; the result is cached as JSON inside the compile cache's
  VERSIONED directory (runtime/compile_cache.py) so a backend/version
  switch re-probes and a warm process never pays the probe.
  `query_summary()` combines the peaks with the per-query ledger into
  `rooflineFrac` (achieved bytes/s over the query wall time vs the
  device HBM peak — the same definition bench.py has always used),
  `linkFrac` (link-crossing bytes/s vs the measured H2D link), and
  `bytesPerOutputRow`.

The ledger is deliberately independent of `obs.enabled`: counters keep
working with the bus off (record() just skips the event emission), and
`spark.rapids.tpu.telemetry.enabled=false` reduces every site to one
boolean check.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict, deque
from typing import Dict, List, Optional

from spark_rapids_tpu.obs import events as _events

#: The physical data-movement channels a transfer is tagged with.
#: `ici` is the inter-chip interconnect: bytes moved by mesh collectives
#: (all_to_all / all_gather inside SPMD programs) that never touch a
#: host link — the proof surface for "host bytes went to zero" on an
#: ICI-resident exchange. `dcn` is the cross-host data-center network
#: tier of a multi-host mesh: bytes moved by collectives over the host
#: axis (hierarchical-agg finals, broadcast builds, dictionary
#: reconciliation syncs) — the planner's job is to keep this number
#: far below `ici`.
DIRECTIONS = ("h2d", "d2h", "spill-disk", "shuffle", "ici", "dcn")

#: Peak HBM bandwidth per chip, bytes/s (public TPU specs; the cpu
#: backend gets a nominal DDR figure so fractions stay meaningful).
#: bench.py reads this table too — one source of truth.
DEVICE_PEAK_BW = {
    "TPU v4": 1.2e12,
    "TPU v5e": 8.19e11,
    "TPU v5 lite": 8.19e11,
    "TPU v5p": 2.765e12,
    "TPU v6e": 1.64e12,
    "cpu": 5.0e10,
}

_PROBE_BYTES = 8 << 20          # link probe transfer size
_QUERY_KEEP = 64                # per-query ledgers retained
_TIMELINE_KEEP = 4096           # (ts, reservedBytes) samples retained
_INTERVAL_KEEP = 4096           # per-query busy intervals per kind


def _busy_union(spans) -> List[tuple]:
    """Merge (t0, t1) spans into a sorted disjoint union."""
    out: List[tuple] = []
    for t0, t1 in sorted(spans):
        if t1 <= t0:
            continue
        if out and t0 <= out[-1][1]:
            out[-1] = (out[-1][0], max(out[-1][1], t1))
        else:
            out.append((t0, t1))
    return out


def _overlap_fraction(a_spans, b_spans) -> Optional[float]:
    """|union(a) ∩ union(b)| over the shorter busy total — the
    pipelining figure of merit: 1.0 means the cheaper stage ran
    entirely under the cover of the other; 0.0 means fully
    serialized. None when either timeline is empty."""
    a, b = _busy_union(a_spans), _busy_union(b_spans)
    if not a or not b:
        return None
    inter = 0.0
    i = j = 0
    while i < len(a) and j < len(b):
        lo = max(a[i][0], b[j][0])
        hi = min(a[i][1], b[j][1])
        if hi > lo:
            inter += hi - lo
        if a[i][1] <= b[j][1]:
            i += 1
        else:
            j += 1
    shorter = min(sum(t1 - t0 for t0, t1 in a),
                  sum(t1 - t0 for t0, t1 in b))
    if shorter <= 0:
        return None
    return max(0.0, min(1.0, inter / shorter))


def _cell() -> Dict[str, int]:
    return {"bytes": 0, "ns": 0, "count": 0}


class _QueryLedger:
    """Per-query accumulation (one per queryId, bounded LRU)."""

    __slots__ = ("by_direction", "by_site", "hbm_peak", "hbm_current",
                 "spill_pressure", "final", "enc_actual", "enc_plain",
                 "ici_host_avoided", "labels", "stream", "intervals",
                 "write")

    def __init__(self):
        self.by_direction: Dict[str, Dict[str, int]] = {}
        self.by_site: Dict[str, Dict[str, int]] = {}
        self.hbm_peak = 0
        self.hbm_current = 0
        self.spill_pressure = 0
        self.final: Optional[dict] = None  # end-of-query summary
        # caller-attached attribution (serve/: tenant, priorityClass);
        # merged into query_summary so /queries rows carry their owner
        self.labels: Optional[dict] = None
        # encoded execution: bytes actually staged for encoded columns
        # vs what the decoded representation would have staged
        self.enc_actual = 0
        self.enc_plain = 0
        # host-link bytes an ICI-resident exchange kept off h2d/d2h
        # (the d2h + h2d round trip of the decoded payload the host
        # shuffle path would have moved for the same rows)
        self.ici_host_avoided = 0
        # streaming executor stats (stream/): windowPeakBytes is a max,
        # partitionsStreamed/recoveries are sums
        self.stream: Dict[str, int] = {}
        # busy-interval timeline per kind ("h2d" | "compute"): bounded
        # (t0, t1) monotonic spans feeding overlapFraction
        self.intervals: Dict[str, List[tuple]] = {}
        # commit-protocol write stats (io/commit.py): bytes/files/rows
        # published and job-commit wall time, all sums
        self.write: Dict[str, int] = {}


class TransferLedger:
    """Process-wide data-movement ledger (the compile_cache.stats
    pattern: one module singleton, per-query views carved out of it)."""

    def __init__(self):
        self._lock = threading.Lock()
        self.enabled = True
        self.totals: Dict[str, Dict[str, int]] = {}
        self.sites: Dict[str, Dict[str, int]] = {}
        self._site_dir: Dict[str, str] = {}
        self._queries: "OrderedDict[int, _QueryLedger]" = OrderedDict()
        # HBM occupancy
        self.hbm_reserved = 0
        self.hbm_peak = 0
        self.pressure_events = 0
        self.timeline: deque = deque(maxlen=_TIMELINE_KEEP)
        self.device_epoch = 1  # stamped by hbm_epoch_marker on recovery
        # encoded-execution savings (process totals)
        self.enc_actual = 0
        self.enc_plain = 0
        # host-link bytes ICI collectives kept off h2d/d2h (process)
        self.ici_host_avoided = 0

    # --- transfer recording ---

    def record(self, direction: str, site: str, nbytes: int,
               ns: int = 0, query_id: Optional[int] = None,
               emit: bool = True) -> None:
        """Account one transfer. `query_id` defaults to the calling
        thread's effective query (task scope first — pool threads —
        then the thread's own query scope); `ns` is the wall time the
        caller measured around the transfer (0 when the site dispatches
        asynchronously and has no honest number)."""
        if not self.enabled or nbytes <= 0:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        with self._lock:
            for cell in (self.totals.setdefault(direction, _cell()),
                         self.sites.setdefault(site, _cell()),
                         self._query(qid).by_direction.setdefault(
                             direction, _cell()),
                         self._query(qid).by_site.setdefault(
                             site, _cell())):
                cell["bytes"] += int(nbytes)
                cell["ns"] += int(ns)
                cell["count"] += 1
            self._site_dir[site] = direction
        if emit:
            _events.emit("transfer", direction=direction, site=site,
                         bytes=int(nbytes), ns=int(ns))

    def record_encoded(self, site: str, actual_bytes: int,
                       plain_bytes: int,
                       query_id: Optional[int] = None) -> None:
        """Account one encoded-representation saving: `actual_bytes`
        is what the encoded column stages for transfer, `plain_bytes`
        what its decoded padded layout would have staged. Feeds the
        per-query bytesSavedEncoded / effectiveCompressionRatio
        summary fields (ROADMAP item 2's effective-compression
        metric)."""
        if not self.enabled or plain_bytes <= 0:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        with self._lock:
            self.enc_actual += int(actual_bytes)
            self.enc_plain += int(plain_bytes)
            q = self._query(qid)
            q.enc_actual += int(actual_bytes)
            q.enc_plain += int(plain_bytes)

    def record_ici(self, site: str, nbytes: int,
                   host_equiv_bytes: int = 0,
                   query_id: Optional[int] = None) -> None:
        """Account one mesh collective: `nbytes` crossed the ICI
        fabric inside an SPMD program (static send-buffer bytes x mesh
        size, derived at trace time — collectives cannot self-report
        from inside jit); `host_equiv_bytes` is what the host-shuffle
        path would have moved over h2d+d2h for the same payload (the
        decoded-layout round trip), feeding the per-query
        `hostBytesAvoided` summary field."""
        if not self.enabled or nbytes <= 0:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        self.record("ici", site, nbytes, query_id=qid)
        if host_equiv_bytes > 0:
            with self._lock:
                self.ici_host_avoided += int(host_equiv_bytes)
                self._query(qid).ici_host_avoided += \
                    int(host_equiv_bytes)

    def record_dcn(self, site: str, nbytes: int,
                   query_id: Optional[int] = None) -> None:
        """Account one CROSS-HOST mesh collective: `nbytes` crossed the
        DCN tier of a multi-host mesh (collectives over the host axis —
        per-shard static bytes x shard count, derived at trace time
        like record_ici). Separate direction so the ici/dcn placement
        split the topology-aware planner makes is a measured number."""
        if not self.enabled or nbytes <= 0:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        self.record("dcn", site, nbytes, query_id=qid)

    def record_interval(self, kind: str, t0: float, t1: float,
                        query_id: Optional[int] = None) -> None:
        """Account one busy interval of a pipelined stage ("h2d" |
        "compute", monotonic seconds) on the owning query's timeline —
        the substrate for overlapFraction (streaming executor's proof
        that transfer and compute actually overlapped)."""
        if not self.enabled or t1 <= t0:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        if not qid:
            return
        with self._lock:
            spans = self._query(qid).intervals.setdefault(kind, [])
            spans.append((float(t0), float(t1)))
            if len(spans) > _INTERVAL_KEEP:
                del spans[:len(spans) - _INTERVAL_KEEP]

    def record_stream(self, query_id: Optional[int] = None,
                      **fields) -> None:
        """Fold streaming-executor stats into the owning query's
        ledger: *Peak*/*Bytes-max keys (windowPeakBytes) keep the max,
        counters (partitionsStreamed, recoveries) accumulate."""
        if not self.enabled:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        if not qid:
            return
        with self._lock:
            st = self._query(qid).stream
            for k, v in fields.items():
                if v is None:
                    continue
                if k.endswith("PeakBytes") or k.endswith("Budget"):
                    st[k] = max(st.get(k, 0), int(v))
                else:
                    st[k] = st.get(k, 0) + int(v)

    def record_write(self, query_id: Optional[int] = None,
                     **fields) -> None:
        """Fold one committed write job's stats (io/commit.py
        commit_job: bytes, files, rows, jobs, commitMs) into the
        owning query's ledger — the per-query `write` block of
        query_summary."""
        if not self.enabled:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        if not qid:
            return
        with self._lock:
            w = self._query(qid).write
            for k, v in fields.items():
                if v is None:
                    continue
                w[k] = w.get(k, 0) + int(v)

    def record_forwarded(self, fields: dict,
                         query_id: Optional[int] = None) -> None:
        """Fold a worker-forwarded `transfer` event (process pool) into
        the driver ledger and re-emit it on the driver bus under the
        driver's query attribution."""
        self.record(str(fields.get("direction", "shuffle")),
                    str(fields.get("site", "worker")),
                    int(fields.get("bytes") or 0),
                    ns=int(fields.get("ns") or 0),
                    query_id=query_id)

    # --- HBM occupancy (SpillCatalog hooks) ---

    def hbm_global(self, reserved: int) -> None:
        """Called by the device pool after every reserve/release with
        its post-op total; keeps the process timeline + high-water."""
        if not self.enabled:
            return
        with self._lock:
            self.hbm_reserved = reserved
            if reserved > self.hbm_peak:
                self.hbm_peak = reserved
            self.timeline.append((round(time.time(), 6), reserved))

    def hbm_query(self, query_id: int, reserved: int) -> None:
        """Called by the catalog's per-query quota ledger with the
        query's post-op device reservation total."""
        if not self.enabled or not query_id:
            return
        with self._lock:
            q = self._query(query_id)
            q.hbm_current = reserved
            if reserved > q.hbm_peak:
                q.hbm_peak = reserved

    def hbm_pressure(self, target: int, freed: int,
                     query_id: Optional[int] = None) -> None:
        """A failed device reservation forced a synchronous spill."""
        if not self.enabled:
            return
        qid = query_id if query_id is not None \
            else _events.effective_query_id()
        with self._lock:
            self.pressure_events += 1
            if qid:
                self._query(qid).spill_pressure += 1

    # --- views ---

    def _query(self, qid: int) -> _QueryLedger:
        """Under lock: the (possibly new) ledger for a query id."""
        q = self._queries.get(qid)
        if q is None:
            q = self._queries[qid] = _QueryLedger()
            while len(self._queries) > _QUERY_KEEP:
                self._queries.popitem(last=False)
        return q

    def query_summary(self, query_id: int,
                      wall_s: Optional[float] = None,
                      output_rows: Optional[int] = None) -> dict:
        """One query's data-movement report: bytes moved by direction
        and site, HBM footprint peak, and — when the caller supplies
        the query wall time — rooflineFrac/linkFrac."""
        if not self.enabled:
            return {}
        with self._lock:
            q = self._queries.get(query_id)
            by_dir = {} if q is None else {
                d: dict(c) for d, c in q.by_direction.items()}
            by_site = {} if q is None else {
                s: dict(c) for s, c in q.by_site.items()}
            hbm_peak = 0 if q is None else q.hbm_peak
            pressure = 0 if q is None else q.spill_pressure
            enc_actual = 0 if q is None else q.enc_actual
            enc_plain = 0 if q is None else q.enc_plain
            ici_avoided = 0 if q is None else q.ici_host_avoided
            labels = None if q is None or not q.labels \
                else dict(q.labels)
            stream = {} if q is None else dict(q.stream)
            write = {} if q is None else dict(q.write)
            intervals = {} if q is None else {
                k: list(v) for k, v in q.intervals.items()}
        total = sum(c["bytes"] for c in by_dir.values())
        link = sum(by_dir.get(d, _cell())["bytes"]
                   for d in ("h2d", "d2h"))
        out = {
            "bytesMoved": {d: by_dir[d]["bytes"] for d in sorted(by_dir)},
            "bytesMovedTotal": total,
            "transfers": sum(c["count"] for c in by_dir.values()),
            "perSite": by_site,
            "hbmPeakBytes": hbm_peak,
            "spillPressureEvents": pressure,
        }
        if labels:
            out["labels"] = labels
        ici = by_dir.get("ici", _cell())["bytes"]
        if ici > 0:
            # ICI-resident shuffle: bytes that rode the mesh fabric
            # instead of the host links, and the h2d+d2h round trip
            # of the decoded payload those collectives displaced
            out["iciBytes"] = ici
            out["hostBytesAvoided"] = ici_avoided
        dcn = by_dir.get("dcn", _cell())["bytes"]
        if dcn > 0:
            # multi-host mesh: bytes that had to cross the slow DCN
            # tier (hierarchical finals / broadcast builds) — compare
            # against iciBytes to see the planner's placement win
            out["dcnBytes"] = dcn
        if stream:
            # streaming executor (stream/): window high-water, how many
            # partition units streamed through it, and the measured
            # H2D/compute busy-interval overlap — the out-of-core
            # pipelining proof (overlapFraction > 0 means transfer hid
            # under compute or vice versa; None when a stage timeline
            # is empty)
            out["windowPeakBytes"] = stream.get("windowPeakBytes", 0)
            out["partitionsStreamed"] = stream.get(
                "partitionsStreamed", 0)
            if stream.get("recoveries"):
                out["streamRecoveries"] = stream["recoveries"]
            frac = _overlap_fraction(intervals.get("h2d", ()),
                                     intervals.get("compute", ()))
            if frac is not None:
                out["overlapFraction"] = round(frac, 4)
        if write:
            # commit-protocol writes (io/commit.py): what this query
            # published and how long the job commit(s) took
            out["write"] = write
        if enc_plain > 0 and enc_actual > 0:
            # encoded execution's measured win: bytes the dictionary
            # representation kept OFF the staging/transfer paths, and
            # the resulting effective compression of those columns
            out["bytesSavedEncoded"] = enc_plain - enc_actual
            out["effectiveCompressionRatio"] = round(
                enc_plain / enc_actual, 3)
        if output_rows:
            out["bytesPerOutputRow"] = round(total / output_rows, 3)
        if wall_s and wall_s > 0:
            peaks = link_peaks()
            out["wallMs"] = round(wall_s * 1000, 3)
            out["rooflineFrac"] = round(
                (total / wall_s) / peaks["devicePeakBytesPerS"], 6)
            if peaks.get("h2dBytesPerS"):
                out["linkFrac"] = round(
                    (link / wall_s) / peaks["h2dBytesPerS"], 6)
        return out

    def label_query(self, query_id: int, **labels) -> None:
        """Attach attribution labels (serve/server.py: tenant,
        priorityClass) to a query's ledger; they ride every later
        query_summary / recent_query_summaries row under `labels`, so
        /queries shows WHOSE bytes each query moved."""
        if not self.enabled or not query_id or not labels:
            return
        with self._lock:
            q = self._query(query_id)
            q.labels = {**(q.labels or {}), **labels}

    def query_labels(self, query_id: int) -> dict:
        with self._lock:
            q = self._queries.get(query_id)
            return dict(q.labels) if q is not None and q.labels else {}

    def merge_final(self, query_id: int, patch: dict) -> None:
        """Patch keys into an already-finalized query summary — the
        write path's hook: a save() collects (which finalizes the
        read-side summary) and only THEN commits its output, so the
        `write` block lands by merge instead of racing finalization."""
        if not self.enabled or not query_id or not patch:
            return
        with self._lock:
            q = self._queries.get(query_id)
            if q is not None and q.final:
                q.final.update(patch)

    def finalize_query(self, query_id: int, summary: dict) -> None:
        """Retain a query's end-of-run summary (with wall time and
        roofline fractions) so /metrics and /queries report finished
        queries with their full numbers."""
        if not self.enabled or not query_id or not summary:
            return
        with self._lock:
            self._query(query_id).final = dict(summary)

    def recent_query_summaries(self) -> Dict[int, dict]:
        """Summaries of the retained queries, most recent last (the
        /queries and /metrics per-query payload): the finalized
        end-of-run summary (with roofline fractions) for finished
        queries, the live ledger view for in-flight ones."""
        with self._lock:
            # labels may land AFTER finalization (serve learns the
            # query id from the collect record) — merge at read time
            finals = {qid: ({**q.final, "labels": dict(q.labels)}
                            if q.labels else dict(q.final))
                      for qid, q in self._queries.items()
                      if qid and q.final}
            live = [qid for qid, q in self._queries.items()
                    if qid and not q.final]
        out = {qid: self.query_summary(qid) for qid in live}
        out.update(finals)
        return out

    def registry_view(self) -> dict:
        """Numeric process-level snapshot for the unified registry
        (obs/registry.py flatten -> plain Prometheus gauges)."""
        with self._lock:
            return {
                "hbm": {"reservedBytes": self.hbm_reserved,
                        "peakBytes": self.hbm_peak,
                        "pressureEvents": self.pressure_events,
                        "deviceEpoch": self.device_epoch},
                "bytesMoved": {d: c["bytes"]
                               for d, c in self.totals.items()},
                "transfers": {d: c["count"]
                              for d, c in self.totals.items()},
                "encoded": {"actualBytes": self.enc_actual,
                            "plainBytes": self.enc_plain,
                            "savedBytes": max(
                                0, self.enc_plain - self.enc_actual)},
                "ici": {"bytes": self.totals.get(
                            "ici", _cell())["bytes"],
                        "hostBytesAvoided": self.ici_host_avoided},
                "dcn": {"bytes": self.totals.get(
                            "dcn", _cell())["bytes"]},
            }

    def site_rows(self) -> List[dict]:
        """Per-site process totals for the labeled Prometheus family:
        [{site, direction, bytes, ns, count}]."""
        with self._lock:
            return [{"site": s, "direction": self._site_dir.get(s, ""),
                     **c} for s, c in sorted(self.sites.items())]

    def hbm_timeline(self, last: int = 512) -> List[list]:
        """The most recent (ts, reservedBytes) occupancy samples."""
        with self._lock:
            return [list(x) for x in list(self.timeline)[-last:]]

    def hbm_epoch_marker(self, epoch: int) -> None:
        """Device-loss recovery marker: stamp the HBM occupancy
        timeline with the post-recovery reservation level (the lost
        DEVICE-tier releases have already walked the level down
        through hbm_global) so a reader sees the reset edge and which
        epoch owns the samples after it."""
        if not self.enabled:
            return
        with self._lock:
            self.device_epoch = epoch
            self.timeline.append(
                (round(time.time(), 6), self.hbm_reserved,
                 f"epoch={epoch}"))


ledger = TransferLedger()

# module-level aliases: instrumented sites stay one short call
record = ledger.record
record_encoded = ledger.record_encoded
record_ici = ledger.record_ici
record_dcn = ledger.record_dcn
record_forwarded = ledger.record_forwarded
record_interval = ledger.record_interval
record_stream = ledger.record_stream
record_write = ledger.record_write
merge_final = ledger.merge_final
hbm_global = ledger.hbm_global
hbm_query = ledger.hbm_query
hbm_pressure = ledger.hbm_pressure
hbm_epoch_marker = ledger.hbm_epoch_marker
query_summary = ledger.query_summary


def _tree_bytes(x) -> int:
    """Total byte size of a jax pytree's array leaves (0 for leaves
    without nbytes — python scalars ride along for free)."""
    import jax

    return sum(getattr(leaf, "nbytes", 0)
               for leaf in jax.tree_util.tree_leaves(x))


def ledgered_put(x, site: str, device=None):
    """`jax.device_put` with the crossing ledgered — the wrapper the
    raw-transfer lint rule (tools/lint) steers every H2D site through
    when it is not already inside an instrumented function. Also a
    device-loss classification point (runtime/device_monitor.py): an
    upload into a dead backend fences the engine for warm recovery
    instead of leaking a raw XlaRuntimeError."""
    import time as _time

    import jax

    from spark_rapids_tpu.runtime import device_monitor

    nbytes = _tree_bytes(x)
    t0 = _time.monotonic_ns()
    with device_monitor.guard(f"transfer.h2d:{site}"):
        out = jax.device_put(x) if device is None \
            else jax.device_put(x, device)
    record("h2d", site, nbytes, ns=_time.monotonic_ns() - t0)
    return out


def ledgered_get(x, site: str):
    """`jax.device_get` with the crossing ledgered; covers everything
    from full-column D2H pulls down to the scalar syncs (row counts,
    ANSI flags) that would otherwise leak out of the movement
    accounting. Fatal-classified like ledgered_put — a D2H sync is
    where a wedged device usually first surfaces."""
    import time as _time

    import jax

    from spark_rapids_tpu.runtime import device_monitor

    t0 = _time.monotonic_ns()
    with device_monitor.guard(f"transfer.d2h:{site}"):
        out = jax.device_get(x)
    record("d2h", site, _tree_bytes(out),
           ns=_time.monotonic_ns() - t0)
    return out


def configure(conf=None) -> None:
    """Session-lifecycle hook: honor spark.rapids.tpu.telemetry.enabled
    (counters persist across sessions like every process ledger)."""
    from spark_rapids_tpu.config import rapids_conf as rc

    if conf is not None:
        ledger.enabled = bool(conf.get(rc.TELEMETRY_ENABLED))


# ------------------------------------------------------- roofline peaks

_peaks: Optional[dict] = None
_peaks_lock = threading.Lock()
_PEAKS_FILE = "telemetry_peaks.json"


def _device_peak_bw(kind: str) -> float:
    return next((v for k, v in DEVICE_PEAK_BW.items()
                 if k.lower() in str(kind).lower()),
                DEVICE_PEAK_BW["cpu"])


def _peaks_path() -> Optional[str]:
    from spark_rapids_tpu.runtime import compile_cache

    root = compile_cache.cache_dir()
    if root is None:
        return None
    # the versioned dir: _check_version_stamp wipes it (and this file)
    # whenever the jax/jaxlib/plugin/backend tuple changes, which is
    # exactly the set of events that invalidates a link measurement
    return os.path.join(root, _PEAKS_FILE)


def _probe_link() -> dict:
    """Measure the host<->device link once: a timed device_put (H2D)
    and device_get (D2H) of a fixed buffer, plus the device HBM peak
    from the spec table."""
    import jax
    import numpy as np

    dev = jax.devices()[0]
    kind = str(getattr(dev, "device_kind", dev.platform))
    buf = np.zeros(_PROBE_BYTES // 8, dtype=np.float64)
    t0 = time.perf_counter()
    on_dev = jax.block_until_ready(jax.device_put(buf))
    h2d_s = max(time.perf_counter() - t0, 1e-9)
    t0 = time.perf_counter()
    jax.device_get(on_dev)
    d2h_s = max(time.perf_counter() - t0, 1e-9)
    return {
        "deviceKind": kind,
        "devicePeakBytesPerS": _device_peak_bw(kind),
        "h2dBytesPerS": round(buf.nbytes / h2d_s, 1),
        "d2hBytesPerS": round(buf.nbytes / d2h_s, 1),
        "probeBytes": buf.nbytes,
    }


def link_peaks(refresh: bool = False) -> dict:
    """Measured link + device peaks, probed once and cached — first in
    process memory, then (when the compile cache is configured) as JSON
    in its versioned directory so restarted processes skip the probe."""
    global _peaks
    with _peaks_lock:
        if _peaks is not None and not refresh:
            return _peaks
        path = _peaks_path()
        if path is not None and not refresh:
            try:
                with open(path) as f:
                    loaded = json.load(f)
                if isinstance(loaded, dict) and "devicePeakBytesPerS" \
                        in loaded:
                    _peaks = loaded
                    return _peaks
            except (OSError, ValueError):
                pass
        try:
            _peaks = _probe_link()
        except Exception:
            # no backend (stubbed jax, probe crash): spec-table only
            _peaks = {"deviceKind": "unknown",
                      "devicePeakBytesPerS": DEVICE_PEAK_BW["cpu"],
                      "h2dBytesPerS": 0.0, "d2hBytesPerS": 0.0,
                      "probeBytes": 0}
        if path is not None:
            try:
                tmp = path + ".tmp"
                with open(tmp, "w") as f:
                    json.dump(_peaks, f)
                os.replace(tmp, path)
            except OSError:
                pass
        return _peaks
