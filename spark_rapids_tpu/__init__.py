"""spark-rapids-tpu: a TPU-native Spark-accelerator-class columnar SQL engine.

This package provides the capabilities of the NVIDIA RAPIDS Accelerator for
Apache Spark (reference: /root/reference, liurenjie1024/spark-rapids
24.04.0-SNAPSHOT) re-designed TPU-first:

- Columnar operators (scan/project/filter/hash-aggregate/join/sort/window/
  exchange) whose kernels are XLA computations over Arrow-layout device
  buffers (reference L4, SURVEY.md section 2.5) instead of cuDF/CUDA calls.
- A planner/override engine that tags each plan node for device placement
  with per-type support checks and explain output (reference
  GpuOverrides.scala / RapidsMeta.scala / TypeChecks.scala).
- A device runtime with a reservation-based HBM budget, DEVICE->HOST->DISK
  spill catalog, OOM retry/split execution and a task-admission semaphore
  (reference RapidsBufferCatalog.scala, RmmRapidsRetryIterator.scala,
  GpuSemaphore.scala).
- A shuffle layer: host-serialized shuffle v1 plus an ICI all-to-all
  collective transport over a jax.sharding.Mesh replacing the reference's
  UCX P2P transport (reference sql-plugin/.../shuffle/, shuffle-plugin/).

The engine is standalone (no JVM): it ships its own Spark-compatible
DataFrame frontend and a CPU (pyarrow) execution backend that doubles as
the differential-testing oracle, mirroring the reference's CPU-vs-GPU
integration test strategy (SURVEY.md section 4).
"""

import jax as _jax

# Spark semantics require 64-bit integers (LongType, TimestampType) and
# float64 (DoubleType). TPU v5 executes both (f64 via emulation), verified
# at import in runtime/device_manager.py.
_jax.config.update("jax_enable_x64", True)

__version__ = "0.1.0"

from spark_rapids_tpu.api.session import TpuSparkSession  # noqa: E402,F401
from spark_rapids_tpu.explain import (  # noqa: E402,F401
    explain_potential_tpu_plan,
)
