"""In-process client for the query service protocol.

`ServeClient` speaks the serve/protocol.py wire format over a plain
TCP socket: connect, hello-bind a tenant + priority class, then
`query()` returns arrow tables and raises the same governance
exception taxonomy the embedded API raises — a served
QueryRejectedError(reason="draining") and an in-process one look
identical to caller code, which is what lets the CI soak share its
oracle with the embedded path.

The client is intentionally dependency-free beyond pyarrow (socket +
json + the protocol module), one socket per client, thread-unsafe by
design: a client IS a session. Concurrency = more clients."""

from __future__ import annotations

import itertools
import socket
from typing import Dict, Optional

import pyarrow as pa

from spark_rapids_tpu.serve import protocol


class ServeError(RuntimeError):
    """A server error frame that maps onto no governance exception
    (protocol violations, internal errors); carries the wire code."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


def _raise_for(header: dict) -> None:
    from spark_rapids_tpu.runtime.errors import (
        QueryCancelledError,
        QueryDeadlineExceeded,
        QueryQuarantinedError,
        QueryRejectedError,
    )

    code = header.get("code", "internal")
    msg = header.get("message", "")
    if code in ("rejected", "draining", "device_fenced",
                "tenant_quota"):
        reason = header.get("reason") or {
            "draining": "draining",
            "device_fenced": "device fenced",
            "tenant_quota": "tenant quota"}.get(code, "rejected")
        raise QueryRejectedError(msg, reason=reason)
    if code == "deadline":
        raise QueryDeadlineExceeded(msg)
    if code == "quarantined":
        raise QueryQuarantinedError(msg)
    if code == "cancelled":
        raise QueryCancelledError(msg)
    raise ServeError(code, msg)


class ServeClient:
    """One tenant-bound connection to a QueryServiceDaemon."""

    def __init__(self, host: str, port: int, tenant: str,
                 priority_class: str = "standard",
                 max_frame_bytes: int = 64 << 20,
                 connect_timeout_s: float = 10.0):
        self.tenant = tenant
        self.priority_class = priority_class
        self.max_frame_bytes = int(max_frame_bytes)
        self._ids = itertools.count(1)
        self._sock = socket.create_connection(
            (host, int(port)), timeout=connect_timeout_s)
        self._sock.settimeout(None)  # queries block until served
        protocol.send_json(self._sock, {
            "type": "hello", "id": next(self._ids),
            "version": protocol.PROTOCOL_VERSION,
            "tenant": tenant, "priorityClass": priority_class})
        reply, _ = protocol.recv_message(self._sock,
                                         self.max_frame_bytes)
        if reply.get("type") != "hello_ok":
            self.close()
            _raise_for(reply)
        self.priority = reply.get("priority", 0)

    @classmethod
    def connect(cls, daemon, tenant: str,
                priority_class: str = "standard") -> "ServeClient":
        """Client for an in-process daemon (tests, bench)."""
        return cls(daemon.host, daemon.port, tenant,
                   priority_class=priority_class,
                   max_frame_bytes=daemon.max_frame_bytes)

    # ------------------------------------------------------- requests

    def query(self, spec: dict,
              params: Optional[Dict[str, object]] = None,
              timeout_ms: Optional[int] = None) -> pa.Table:
        """Run a spec; returns the arrow result or raises the mapped
        governance error. `self.last_result` keeps the result header
        (queryId, planCache verdict, rows, wallMs)."""
        req = {"type": "query", "id": next(self._ids), "spec": spec}
        if params:
            req["params"] = params
        if timeout_ms is not None:
            req["timeoutMs"] = int(timeout_ms)
        protocol.send_json(self._sock, req)
        header, table = protocol.recv_message(self._sock,
                                              self.max_frame_bytes)
        if header.get("type") == "error":
            _raise_for(header)
        self.last_result = header
        return table

    def cancel(self, query_id: Optional[int] = None) -> int:
        """Cancel one engine query id, or everything in flight when
        None — TENANT-SCOPED either way: the server only unwinds
        queries this connection's own tenant submitted (another
        tenant's id counts 0). Cross-tenant cancel is an in-process
        operator action (admission.get().cancel/cancel_all)."""
        req = {"type": "cancel", "id": next(self._ids)}
        if query_id is not None:
            req["queryId"] = int(query_id)
        protocol.send_json(self._sock, req)
        reply, _ = protocol.recv_message(self._sock,
                                         self.max_frame_bytes)
        if reply.get("type") == "error":
            _raise_for(reply)
        return int(reply.get("cancelled", 0))

    def ping(self) -> dict:
        protocol.send_json(self._sock, {"type": "ping",
                                        "id": next(self._ids)})
        reply, _ = protocol.recv_message(self._sock,
                                         self.max_frame_bytes)
        return reply

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is None:
            return
        try:
            protocol.send_json(sock, {"type": "bye",
                                      "id": next(self._ids)})
            sock.settimeout(2.0)
            protocol.recv_json(sock, self.max_frame_bytes)
        except (OSError, protocol.ProtocolError, ConnectionError):
            pass
        try:
            sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *_exc) -> bool:
        self.close()
        return False
