"""In-process client for the query service protocol.

`ServeClient` speaks the serve/protocol.py wire format over a plain
TCP socket: connect, hello-bind a tenant + priority class, then
`query()` returns arrow tables and raises the same governance
exception taxonomy the embedded API raises — a served
QueryRejectedError(reason="draining") and an in-process one look
identical to caller code, which is what lets the CI soak share its
oracle with the embedded path.

The client is intentionally dependency-free beyond pyarrow (socket +
json + the protocol module), one socket per client, thread-unsafe by
design: a client IS a session. Concurrency = more clients."""

from __future__ import annotations

import itertools
import socket
from typing import Dict, Optional

import pyarrow as pa

from spark_rapids_tpu.serve import protocol


class ServeError(RuntimeError):
    """A server error frame that maps onto no governance exception
    (protocol violations, internal errors); carries the wire code."""

    def __init__(self, code: str, message: str):
        super().__init__(f"[{code}] {message}")
        self.code = code


def _raise_for(header: dict) -> None:
    from spark_rapids_tpu.runtime.errors import (
        QueryCancelledError,
        QueryDeadlineExceeded,
        QueryQuarantinedError,
        QueryRejectedError,
    )

    code = header.get("code", "internal")
    msg = header.get("message", "")
    if code in ("rejected", "draining", "device_fenced",
                "tenant_quota", "unavailable"):
        reason = header.get("reason") or {
            "draining": "draining",
            "device_fenced": "device fenced",
            "tenant_quota": "tenant quota",
            "unavailable": "unavailable"}.get(code, "rejected")
        exc: BaseException = QueryRejectedError(msg, reason=reason)
    elif code == "deadline":
        exc = QueryDeadlineExceeded(msg)
    elif code == "quarantined":
        exc = QueryQuarantinedError(msg)
    elif code == "cancelled":
        exc = QueryCancelledError(msg)
    else:
        exc = ServeError(code, msg)
    # backpressure hint from busy/draining frames rides the exception
    # so callers (and the fleet router) can honor it
    exc.retry_after_ms = int(header.get("retryAfterMs") or 0)
    raise exc


def _connect_policy(attempts, base_ms, max_ms):
    """Resolve the connect-retry knobs: explicit args > active session
    conf > entry defaults (a bare client in a fresh process still gets
    sane retry behavior)."""
    from spark_rapids_tpu.api.session import TpuSparkSession
    from spark_rapids_tpu.config import rapids_conf as rc
    from spark_rapids_tpu.runtime.backoff import BackoffPolicy

    s = TpuSparkSession.active()
    conf = s.rapids_conf if s is not None else None

    def pick(explicit, entry):
        if explicit is not None:
            return int(explicit)
        return int(conf.get(entry)) if conf is not None \
            else int(entry.default)

    attempts = max(1, pick(attempts, rc.SERVE_CONNECT_ATTEMPTS))
    return attempts, BackoffPolicy(
        attempts, pick(base_ms, rc.SERVE_CONNECT_BACKOFF_MS),
        pick(max_ms, rc.SERVE_CONNECT_MAX_BACKOFF_MS))


class ServeClient:
    """One tenant-bound connection to a QueryServiceDaemon."""

    def __init__(self, host: str, port: int, tenant: str,
                 priority_class: str = "standard",
                 max_frame_bytes: int = 64 << 20,
                 connect_timeout_s: float = 10.0,
                 connect_attempts: Optional[int] = None,
                 connect_backoff_ms: Optional[int] = None,
                 connect_max_backoff_ms: Optional[int] = None):
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import backoff, cancellation
        from spark_rapids_tpu.runtime.errors import QueryRejectedError

        self.tenant = tenant
        self.priority_class = priority_class
        self.max_frame_bytes = int(max_frame_bytes)
        self._ids = itertools.count(1)
        self._sock = None
        # a replica restarting under the fleet supervisor refuses TCP
        # for its boot window — ride the shared backoff curve instead
        # of surfacing ConnectionRefusedError on the first slam
        attempts, policy = _connect_policy(
            connect_attempts, connect_backoff_ms,
            connect_max_backoff_ms)
        hint_ms = 0
        last_exc: Optional[BaseException] = None
        for attempt in range(attempts):
            if attempt:
                delay_s = max(policy.delay_s(attempt - 1),
                              hint_ms / 1000.0)
                backoff.record_retry("serve.connect")
                obs_events.emit(
                    "serve.retry", site="serve.connect",
                    attempt=attempt,
                    delayMs=round(delay_s * 1000.0, 1))
                cancellation.sleep_interruptible(delay_s)
            try:
                self._connect_once(host, port, connect_timeout_s)
                return
            except (ConnectionError, OSError, socket.timeout) as e:
                last_exc, hint_ms = e, 0
            except QueryRejectedError as e:
                # a draining replica refused cleanly: retryable, and
                # its retryAfterMs hint floors the next delay
                if getattr(e, "reason", "") != "draining":
                    raise
                last_exc = e
                hint_ms = getattr(e, "retry_after_ms", 0)
            except ServeError as e:
                if e.code != "busy":
                    raise
                last_exc = e
                hint_ms = getattr(e, "retry_after_ms", 0)
        raise last_exc

    def _connect_once(self, host: str, port: int,
                      connect_timeout_s: float) -> None:
        sock = socket.create_connection(
            (host, int(port)), timeout=connect_timeout_s)
        try:
            sock.settimeout(None)  # queries block until served
            protocol.send_json(sock, {
                "type": "hello", "id": next(self._ids),
                "version": protocol.PROTOCOL_VERSION,
                "tenant": self.tenant,
                "priorityClass": self.priority_class})
            reply, _ = protocol.recv_message(sock,
                                             self.max_frame_bytes)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        if reply.get("type") != "hello_ok":
            try:
                sock.close()
            except OSError:
                pass
            _raise_for(reply)
        self._sock = sock
        self.priority = reply.get("priority", 0)

    @classmethod
    def connect(cls, daemon, tenant: str,
                priority_class: str = "standard") -> "ServeClient":
        """Client for an in-process daemon (tests, bench)."""
        return cls(daemon.host, daemon.port, tenant,
                   priority_class=priority_class,
                   max_frame_bytes=daemon.max_frame_bytes)

    # ------------------------------------------------------- requests

    def query(self, spec: dict,
              params: Optional[Dict[str, object]] = None,
              timeout_ms: Optional[int] = None,
              request_id: Optional[str] = None) -> pa.Table:
        """Run a spec; returns the arrow result or raises the mapped
        governance error. `self.last_result` keeps the result header
        (queryId, planCache verdict, rows, wallMs). `request_id` is
        the idempotency key: resubmitting the same id replays the
        retained result (header carries `dedupe: true`) instead of
        re-executing — how a caller retries a lost connection without
        risking double execution or double billing."""
        req = {"type": "query", "id": next(self._ids), "spec": spec}
        if params:
            req["params"] = params
        if timeout_ms is not None:
            req["timeoutMs"] = int(timeout_ms)
        if request_id is not None:
            req["requestId"] = str(request_id)
        protocol.send_json(self._sock, req)
        header, table = protocol.recv_message(self._sock,
                                              self.max_frame_bytes)
        if header.get("type") == "error":
            _raise_for(header)
        self.last_result = header
        return table

    def cancel(self, query_id: Optional[int] = None) -> int:
        """Cancel one engine query id, or everything in flight when
        None — TENANT-SCOPED either way: the server only unwinds
        queries this connection's own tenant submitted (another
        tenant's id counts 0). Cross-tenant cancel is an in-process
        operator action (admission.get().cancel/cancel_all)."""
        req = {"type": "cancel", "id": next(self._ids)}
        if query_id is not None:
            req["queryId"] = int(query_id)
        protocol.send_json(self._sock, req)
        reply, _ = protocol.recv_message(self._sock,
                                         self.max_frame_bytes)
        if reply.get("type") == "error":
            _raise_for(reply)
        return int(reply.get("cancelled", 0))

    def ping(self) -> dict:
        protocol.send_json(self._sock, {"type": "ping",
                                        "id": next(self._ids)})
        reply, _ = protocol.recv_message(self._sock,
                                         self.max_frame_bytes)
        return reply

    def status(self) -> dict:
        """The daemon's status() snapshot over the wire (fleet CI
        reconciles billing/dedupe across replicas through this)."""
        protocol.send_json(self._sock, {"type": "status",
                                        "id": next(self._ids)})
        reply, _ = protocol.recv_message(self._sock,
                                         self.max_frame_bytes)
        if reply.get("type") == "error":
            _raise_for(reply)
        return reply.get("status") or {}

    def close(self) -> None:
        sock, self._sock = self._sock, None
        if sock is None:
            return
        try:
            protocol.send_json(sock, {"type": "bye",
                                      "id": next(self._ids)})
            sock.settimeout(2.0)
            protocol.recv_json(sock, self.max_frame_bytes)
        except (OSError, protocol.ProtocolError, ConnectionError):
            pass
        try:
            sock.close()
        except OSError:
            pass

    def __enter__(self) -> "ServeClient":
        return self

    def __exit__(self, *_exc) -> bool:
        self.close()
        return False
