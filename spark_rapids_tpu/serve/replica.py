"""Replica process entry point — `python -m spark_rapids_tpu.serve.replica`.

One fleet replica is one OS process owning one warm TpuSparkSession
behind one QueryServiceDaemon. The ReplicaSupervisor
(serve/supervisor.py) spawns this module with three env vars:

- SRTPU_REPLICA_NAME   replica name (events, status, ready file)
- SRTPU_REPLICA_CONF   JSON settings dict for the owned session —
                       serve.port=0 (ephemeral; the real port travels
                       back via the ready file) and, when the fleet
                       partitions chips, the replica's
                       spark.rapids.tpu.mesh subset
- SRTPU_REPLICA_READY  path the replica atomically writes (tmp +
                       rename) once it is accepting:
                       {"name", "pid", "port", "httpPort"}

Lifecycle: session + daemon come up, the obs HTTP endpoint binds an
ephemeral port (its /readyz carries the admission `load` block the
router polls), signal handlers install (first SIGTERM drains
gracefully, a second escalates — server.py handle_term_signal), the
ready file lands, and the main thread parks until the daemon reaches
`stopped`. Crash-looping, restarts and SIGKILL escalation live in the
supervisor; this process only has to serve and die cleanly.
"""

from __future__ import annotations

import json
import os
import sys


def main() -> int:
    name = os.environ.get("SRTPU_REPLICA_NAME") or \
        f"replica-{os.getpid()}"
    settings = json.loads(os.environ.get("SRTPU_REPLICA_CONF") or "{}")
    ready_path = os.environ.get("SRTPU_REPLICA_READY") or ""

    from spark_rapids_tpu.obs.http import ObsHttpServer
    from spark_rapids_tpu.runtime import cancellation
    from spark_rapids_tpu.serve.server import QueryServiceDaemon

    daemon = QueryServiceDaemon(conf=settings, name=name)
    daemon.start()
    daemon.install_signal_handlers()
    try:
        http = ObsHttpServer(daemon.session, port=0)
    except OSError:
        http = None  # health falls back to the router's TCP probe
    if ready_path:
        tmp = f"{ready_path}.tmp"
        with open(tmp, "w") as f:
            json.dump({"name": name, "pid": os.getpid(),
                       "port": daemon.port,
                       "httpPort": http.port if http else None}, f)
        os.replace(tmp, ready_path)
    while daemon.state != "stopped":
        cancellation.sleep_interruptible(0.1)
    if http is not None:
        http.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
