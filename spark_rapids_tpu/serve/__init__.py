"""Multi-tenant query service — the persistent serving layer.

The engine's governance substrate (admission tiers, per-query cancel
tokens, transfer-ledger billing, device-loss fencing, drain-aware
readiness) was built bottom-up across prior PRs; this package is the
server that finally fronts it: ONE warm `TpuSparkSession` multiplexed
across many concurrent client connections, each bound to a tenant id
and a named priority class.

- serve/protocol.py — length-prefixed JSON/Arrow-IPC wire protocol
- serve/spec.py     — the JSON query-spec DSL -> DataFrame compiler
- serve/plan_cache.py — structural plan cache (literals parameterized
  out, compile-cache-style digest keying, per-tenant isolation)
- serve/tenants.py  — per-tenant quota ledgers + billing totals
- serve/server.py   — the daemon: TCP accept loop, graceful drain,
  SIGTERM, liveness/readiness integration
- serve/client.py   — in-process client speaking the same protocol
"""

from spark_rapids_tpu.serve.client import ServeClient, ServeError
from spark_rapids_tpu.serve.plan_cache import PlanCache
from spark_rapids_tpu.serve.server import QueryServiceDaemon
from spark_rapids_tpu.serve.tenants import TenantLedger

__all__ = ["QueryServiceDaemon", "ServeClient", "ServeError",
           "PlanCache", "TenantLedger"]
