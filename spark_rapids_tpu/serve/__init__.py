"""Multi-tenant query service — the persistent serving layer.

The engine's governance substrate (admission tiers, per-query cancel
tokens, transfer-ledger billing, device-loss fencing, drain-aware
readiness) was built bottom-up across prior PRs; this package is the
server that finally fronts it: ONE warm `TpuSparkSession` multiplexed
across many concurrent client connections, each bound to a tenant id
and a named priority class — and, above that, the FLEET layer that
turns one survivable daemon into a survivable service: N process-per-
replica daemons under a supervisor, behind a health-routed front door
with idempotent failover.

- serve/protocol.py — length-prefixed JSON/Arrow-IPC wire protocol
  (+ requestId idempotency keys, retryAfterMs backpressure hints)
- serve/spec.py     — the JSON query-spec DSL -> DataFrame compiler
- serve/plan_cache.py — structural plan cache (literals parameterized
  out, compile-cache-style digest keying, per-tenant isolation) +
  affinity_key, the router's cross-process hash-ring input
- serve/tenants.py  — per-tenant quota ledgers + billing totals
- serve/server.py   — the daemon: TCP accept loop, graceful drain +
  second-SIGTERM escalation, request-id dedupe window,
  liveness/readiness integration
- serve/client.py   — in-process client speaking the same protocol,
  with conf'd connect retry/backoff
- serve/replica.py  — subprocess entry: one replica process = one
  session + one daemon + ready-file handshake
- serve/supervisor.py — ReplicaSupervisor: spawn/monitor/crash-loop/
  drain the replica processes
- serve/router.py   — FleetRouter: health-gated, affinity-routed
  front door with exactly-once failover
"""

from spark_rapids_tpu.serve.client import ServeClient, ServeError
from spark_rapids_tpu.serve.plan_cache import PlanCache, affinity_key
from spark_rapids_tpu.serve.router import FleetRouter
from spark_rapids_tpu.serve.server import QueryServiceDaemon
from spark_rapids_tpu.serve.supervisor import ReplicaSupervisor
from spark_rapids_tpu.serve.tenants import TenantLedger

__all__ = ["QueryServiceDaemon", "ServeClient", "ServeError",
           "PlanCache", "TenantLedger", "FleetRouter",
           "ReplicaSupervisor", "affinity_key"]
