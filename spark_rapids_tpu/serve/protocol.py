"""Wire protocol of the query service: length-prefixed JSON frames
with Arrow-IPC result payloads.

Every message is one FRAME: a 4-byte big-endian unsigned length
followed by that many bytes of UTF-8 JSON. A message whose header
carries `"payload": "arrow"` is immediately followed by ONE more
frame holding an Arrow IPC stream (the columnar result — the same
arrow tables `collect_arrow` returns, so a result crosses the socket
in its execution layout with no row pivot).

Client -> server message types: `hello` (tenant + priorityClass
binding, protocol version check), `query` (a serve/spec.py query spec
+ parameter bindings), `cancel`, `ping`, `status` (daemon status
snapshot — the fleet gate reconciles billing/dedupe remotely), `bye`.
Server -> client: `hello_ok`, `result`, `error` (stable `code` from
ERROR_CODES + human `message`), `pong`, `status_ok`, `bye_ok`.

Idempotency: a `query` message MAY carry a `requestId` string — the
idempotency key of the fleet layer. A replica remembers recently
completed (and currently in-flight) request ids in a bounded dedupe
window; a resubmitted id is answered from the window (same result
frames, `dedupe: true` on the header) without re-executing or
re-billing. The fleet router mints one per routed request when the
client didn't, which is what makes kill-mid-query failover exactly
-once: the resubmit to a survivor either re-executes (the dead
replica never finished) or replays (it finished but the ack was
lost). `busy`/`draining` error frames MAY carry `retryAfterMs` — a
backpressure hint clients and the router honor instead of
hot-spinning.

Frames are bounded by serve.maxFrameBytes on both sides: an oversized
header/payload is a clean `protocol` error, never an unbounded
buffer. The protocol is deliberately dumb — all governance verdicts
(shed, deadline, quota, drain) travel as error codes mapped from the
QueryGovernanceError taxonomy (runtime/errors.py), so a thin client
in any language can speak it with a socket and a JSON parser.
"""

from __future__ import annotations

import io
import json
import struct
from typing import Optional, Tuple

import pyarrow as pa
import pyarrow.ipc as pa_ipc

PROTOCOL_VERSION = 1

_LEN = struct.Struct(">I")

#: Stable wire error codes (docs/serving.md) — the client maps them
#: back onto the governance exception taxonomy.
ERROR_CODES = (
    "rejected",       # admission shed: queue full / queue timeout
    "draining",       # engine draining: retry against another replica
    "device_fenced",  # fenced for device-loss recovery: retry later
    "tenant_quota",   # per-tenant concurrency/byte cap
    "cancelled",      # cancel() / cancel storm
    "deadline",       # per-query deadline exceeded
    "quarantined",    # poison-query quarantine
    "bad_spec",       # query spec failed to compile
    "protocol",       # malformed/oversized frame, bad handshake
    "busy",           # connection limit reached
    "unavailable",    # fleet router: no routable replica survived
    "internal",       # anything else; message carries the type
)


class ProtocolError(RuntimeError):
    """Framing/handshake violation — the connection is not recoverable
    past it (the stream offset is unknown), so both sides close."""


def send_frame(sock, data: bytes) -> None:
    sock.sendall(_LEN.pack(len(data)) + data)


def _recv_exact(sock, n: int) -> bytes:
    import socket as _socket

    buf = bytearray()
    while len(buf) < n:
        try:
            chunk = sock.recv(n - len(buf))
        except _socket.timeout:
            if buf:
                # mid-frame stall: keep waiting — giving up here would
                # desync the stream; a dead peer surfaces as a closed
                # socket instead
                continue
            raise
        if not chunk:
            raise ConnectionError("peer closed mid-frame")
        buf.extend(chunk)
    return bytes(buf)


def recv_frame(sock, max_bytes: int) -> bytes:
    n = _LEN.unpack(_recv_exact(sock, _LEN.size))[0]
    if n > max_bytes:
        raise ProtocolError(
            f"frame of {n} bytes exceeds serve.maxFrameBytes "
            f"({max_bytes})")
    return _recv_exact(sock, n) if n else b""


def send_json(sock, obj: dict) -> None:
    send_frame(sock, json.dumps(obj).encode("utf-8"))


def recv_json(sock, max_bytes: int) -> dict:
    data = recv_frame(sock, max_bytes)
    try:
        obj = json.loads(data.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ProtocolError(f"frame is not valid JSON: {e}")
    if not isinstance(obj, dict) or "type" not in obj:
        raise ProtocolError("frame is not a {'type': ...} message")
    return obj


def table_to_ipc(table: pa.Table) -> bytes:
    sink = io.BytesIO()
    with pa_ipc.new_stream(sink, table.schema) as writer:
        writer.write_table(table)
    return sink.getvalue()


def ipc_to_table(data: bytes) -> pa.Table:
    with pa_ipc.open_stream(io.BytesIO(data)) as reader:
        return reader.read_all()


def send_result(sock, header: dict, table: pa.Table) -> int:
    """`result` header + Arrow payload frame; returns payload bytes
    (the per-connection egress the tenant ledger bills)."""
    payload = table_to_ipc(table)
    header = {**header, "type": "result", "payload": "arrow",
              "payloadBytes": len(payload)}
    send_json(sock, header)
    send_frame(sock, payload)
    return len(payload)


def recv_message(sock, max_bytes: int
                 ) -> Tuple[dict, Optional[pa.Table]]:
    """One full message: the JSON header plus its Arrow payload frame
    when the header announces one."""
    header = recv_json(sock, max_bytes)
    table = None
    if header.get("payload") == "arrow":
        table = ipc_to_table(recv_frame(sock, max_bytes))
    return header, table


def error_code_for(exc: BaseException) -> str:
    """Governance taxonomy -> stable wire code."""
    from spark_rapids_tpu.runtime.errors import (
        QueryCancelledError,
        QueryDeadlineExceeded,
        QueryQuarantinedError,
        QueryRejectedError,
    )
    from spark_rapids_tpu.serve.spec import SpecError

    if isinstance(exc, QueryRejectedError):
        reason = getattr(exc, "reason", "")
        if reason == "draining":
            return "draining"
        if reason == "device fenced":
            return "device_fenced"
        if reason == "tenant quota":
            return "tenant_quota"
        return "rejected"
    if isinstance(exc, QueryQuarantinedError):
        return "quarantined"
    if isinstance(exc, QueryDeadlineExceeded):
        return "deadline"
    if isinstance(exc, QueryCancelledError):
        return "cancelled"
    if isinstance(exc, SpecError):
        # only the compiler's own taxonomy is a spec error —
        # compile_spec wraps its compile-time ValueError/KeyError/
        # TypeError in SpecError, so engine internals raising the
        # same builtins MID-EXECUTION fall through to 'internal'
        # instead of being misreported to clients as bad specs
        return "bad_spec"
    if isinstance(exc, ProtocolError):
        return "protocol"
    return "internal"
