"""The query service daemon — one warm engine, many tenants.

`QueryServiceDaemon` multiplexes concurrent client connections onto
ONE resident `TpuSparkSession`: every connection binds a tenant id
and a named priority class at hello (serve.priorityClasses), and
every `query` message runs through the full governance stack —
per-tenant quota gate (serve/tenants.py), structural plan cache
(serve/plan_cache.py), admission tiers with the connection's
priority/timeout threaded via `admission.request_overrides`, the
engine ladder, and transfer-ledger billing — on the handler thread of
the connection that sent it (a client wanting intra-tenant
concurrency opens more connections, the thread-per-query model the
admission queue already governs).

Lifecycle is production-grade:

- `drain()` — stop accepting (listener closed, admission sheds new
  submissions with reason='draining', /readyz flips 503 via the
  obs/http readiness probe), let in-flight queries finish under
  serve.drain.timeoutMs, then cancel stragglers through the admission
  cancel machinery. Queued queries keep their slots during the drain
  window — drain is an intake valve, not a kill switch.
- `stop()` — drain, close every socket, join every handler thread
  (leak_report() returns all-zero afterwards), stop the owned
  session.
- SIGTERM (install_signal_handlers, main thread only) — graceful
  stop off the signal, the k8s preStop contract.

Liveness vs readiness: the daemon never dies on a device fence — the
obs HTTP /healthz stays 200 (process alive) while /readyz reports 503
with `fenced`/`fencedChips`/`fencedHosts`/`draining`, so a load
balancer routes around a recovering engine instead of restarting it
and losing the warm compile cache the whole serving layer exists to
keep. A fenced chip or HOST only flips capacity (`fencedChips`/
`fencedHosts` in a still-200 /readyz body): survivors keep serving
over the rebuilt mesh, and a recovered host rejoining bumps capacity
back."""

from __future__ import annotations

import socket
import threading
import time
from typing import Dict, Optional

from spark_rapids_tpu.serve import protocol
from spark_rapids_tpu.serve.tenants import TenantLedger


def parse_priority_classes(spec: str) -> Dict[str, int]:
    """'interactive=100,standard=0,batch=-100' -> {name: weight}."""
    out: Dict[str, int] = {}
    for part in str(spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        if "=" not in part:
            raise ValueError(
                f"bad serve.priorityClasses entry {part!r}: "
                f"expected name=weight")
        name, weight = part.split("=", 1)
        out[name.strip()] = int(weight)
    if not out:
        raise ValueError("serve.priorityClasses is empty")
    return out


_active_daemon = None
_active_lock = threading.Lock()


def active_daemon() -> Optional["QueryServiceDaemon"]:
    """The most recently started daemon in this process, or None —
    the hook obs/registry.unified_snapshot uses to fold serve
    counters into the unified surface."""
    return _active_daemon


class _Connection:
    """One accepted client: its socket, tenant binding, and stats."""

    __slots__ = ("sock", "addr", "tenant", "priority_class",
                 "priority", "queries", "bytes_out", "thread", "dead")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.tenant = ""
        self.priority_class = ""
        self.priority = 0
        self.queries = 0
        self.bytes_out = 0
        self.thread: Optional[threading.Thread] = None
        # a failed send leaves a possibly-partial frame on the wire;
        # the length-prefixed stream is unrecoverable past it, so the
        # message loop closes the connection instead of continuing
        self.dead = False


class _DedupeEntry:
    """One idempotency-key slot: inflight while its owning handler
    executes, done once the result frames are retained for replay."""

    __slots__ = ("key", "state", "header", "payload", "event")

    def __init__(self, key):
        self.key = key
        self.state = "inflight"  # inflight | done | failed
        self.header: Optional[dict] = None
        self.payload: bytes = b""
        self.event = threading.Event()


class _DedupeWindow:
    """Bounded per-replica idempotency window (protocol.py contract):
    a resubmitted request id is answered from here — same result
    frames, no re-execution, no re-billing. Keys are (tenant,
    requestId) so one tenant can never replay (or observe) another's
    results by guessing ids. Only COMPLETED results are retained;
    a failed execution abandons its slot so the resubmit re-runs —
    exactly-once applies to results, errors stay retryable."""

    def __init__(self, max_entries: int, max_bytes: int):
        self.max_entries = int(max_entries)
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        self._inflight: Dict[tuple, _DedupeEntry] = {}
        from collections import OrderedDict

        self._done: "OrderedDict[tuple, _DedupeEntry]" = OrderedDict()
        self._bytes = 0
        self.replays = 0
        self.joins = 0
        self.evictions = 0
        self.completed = 0

    def claim(self, tenant: str, rid: str):
        """-> ('run', entry) caller owns execution; ('wait', entry)
        another handler is executing it; ('replay', entry) done."""
        key = (tenant, rid)
        with self._lock:
            e = self._done.get(key)
            if e is not None:
                self._done.move_to_end(key)
                self.replays += 1
                return "replay", e
            e = self._inflight.get(key)
            if e is not None:
                self.joins += 1
                return "wait", e
            e = _DedupeEntry(key)
            self._inflight[key] = e
            return "run", e

    def complete(self, entry: _DedupeEntry, header: dict,
                 payload: bytes) -> int:
        """Retain the result for replay; returns evictions made."""
        evicted = 0
        with self._lock:
            entry.header = dict(header)
            entry.payload = payload
            entry.state = "done"
            self._inflight.pop(entry.key, None)
            self._done[entry.key] = entry
            self._bytes += len(payload)
            self.completed += 1
            while self._done and (
                    len(self._done) > self.max_entries
                    or self._bytes > self.max_bytes):
                _, old = self._done.popitem(last=False)
                self._bytes -= len(old.payload)
                old.payload = b""
                self.evictions += 1
                evicted += 1
        entry.event.set()
        return evicted

    def abandon(self, entry: _DedupeEntry) -> None:
        with self._lock:
            self._inflight.pop(entry.key, None)
            entry.state = "failed"
        entry.event.set()

    def snapshot(self) -> dict:
        with self._lock:
            return {"entries": len(self._done),
                    "inflight": len(self._inflight),
                    "bytes": self._bytes,
                    "completed": self.completed,
                    "replays": self.replays,
                    "joins": self.joins,
                    "evictions": self.evictions}


class QueryServiceDaemon:
    """TCP front door over one warm TpuSparkSession."""

    def __init__(self, session=None, conf: Optional[dict] = None,
                 name: str = ""):
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.serve.plan_cache import PlanCache

        if session is None:
            from spark_rapids_tpu.api.session import TpuSparkSession

            session = TpuSparkSession(conf or {})
            self._owns_session = True
        else:
            self._owns_session = False
        self.session = session
        self.name = str(name or "")
        cget = session.rapids_conf.get
        self.host = cget(rc.SERVE_HOST)
        self._conf_port = cget(rc.SERVE_PORT)
        self.max_connections = cget(rc.SERVE_MAX_CONNECTIONS)
        self.max_frame_bytes = cget(rc.SERVE_MAX_FRAME_BYTES)
        self.drain_timeout_ms = cget(rc.SERVE_DRAIN_TIMEOUT_MS)
        self.retry_after_ms = cget(rc.SERVE_RETRY_AFTER_MS)
        dedupe_entries = cget(rc.FLEET_DEDUPE_ENTRIES)
        self._dedupe = _DedupeWindow(
            dedupe_entries, cget(rc.FLEET_DEDUPE_MAX_BYTES)) \
            if dedupe_entries > 0 else None
        self.priority_classes = parse_priority_classes(
            cget(rc.SERVE_PRIORITY_CLASSES))
        self.plan_cache = PlanCache(
            max_entries=cget(rc.SERVE_PLAN_CACHE_MAX_ENTRIES),
            bindings_per_entry=cget(rc.SERVE_PLAN_CACHE_BINDINGS),
            enabled=cget(rc.SERVE_PLAN_CACHE_ENABLED))
        self.tenants = TenantLedger(
            max_concurrent=cget(rc.SERVE_TENANT_MAX_CONCURRENT),
            max_device_bytes=cget(rc.SERVE_TENANT_MAX_DEVICE_BYTES))
        self.port: Optional[int] = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._lock = threading.Lock()
        self._conns: Dict[int, _Connection] = {}
        self._conn_seq = 0
        self._in_flight = 0
        self._state = "new"  # new | serving | draining | stopped
        self._admission = None
        self._prev_sigterm = None
        self._queries_served = 0
        self._drain_abort = threading.Event()

    # ------------------------------------------------------ lifecycle

    def start(self) -> "QueryServiceDaemon":
        from spark_rapids_tpu.runtime import admission

        if self._state != "new":
            raise RuntimeError(f"daemon already {self._state}")
        self._admission = admission.get()
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, int(self._conf_port)))
        self._listener.listen(128)
        self._listener.settimeout(0.2)
        self.port = self._listener.getsockname()[1]
        self._state = "serving"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="srtpu-serve-accept",
            daemon=True)
        self._accept_thread.start()
        global _active_daemon
        with _active_lock:
            _active_daemon = self
        return self

    def install_signal_handlers(self) -> bool:
        """SIGTERM -> graceful stop; a SECOND SIGTERM while the drain
        is still waiting escalates (handle_term_signal). Only possible
        on the main thread (signal module contract); returns whether
        it installed."""
        import signal

        if threading.current_thread() is not threading.main_thread():
            return False

        def on_term(_sig, _frm):
            self.handle_term_signal()

        self._prev_sigterm = signal.signal(signal.SIGTERM, on_term)
        return True

    def handle_term_signal(self) -> None:
        """First TERM: graceful stop on a helper thread. A repeat TERM
        during the drain is an operator (or supervisor) saying 'now':
        it cancels the stragglers immediately and aborts the drain
        waits instead of being swallowed by the already-draining
        guard — before this, a wedged drain could only be killed -9.
        Signal-safe: nothing here blocks."""
        from spark_rapids_tpu.obs import events as obs_events

        with self._lock:
            draining = self._state == "draining"
            in_flight = self._in_flight
            n_conns = len(self._conns)
        if not draining:
            threading.Thread(target=self.stop,
                             name="srtpu-serve-sigterm",
                             daemon=True).start()
            return
        obs_events.emit("serve.escalate", inFlight=in_flight,
                        connections=n_conns)
        self._drain_abort.set()
        if self._admission is not None:
            self._admission.cancel_all("drain escalated by signal")

    def drain(self, timeout_ms: Optional[int] = None) -> dict:
        """Graceful intake shutdown; returns the drain report."""
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import cancellation

        with self._lock:
            if self._state != "serving":
                # never started ("new") or already draining/stopped:
                # there is no intake to close and no admission valve
                return {"state": self._state, "cancelled": 0}
            self._state = "draining"
            in_flight = self._in_flight
            n_conns = len(self._conns)
        obs_events.emit("serve.drain", phase="begin",
                        inFlight=in_flight, connections=n_conns)
        self._admission.begin_drain("query service draining")
        self._close_listener()
        deadline = time.monotonic() + (
            self.drain_timeout_ms if timeout_ms is None
            else timeout_ms) / 1000.0
        while time.monotonic() < deadline \
                and not self._drain_abort.is_set():
            with self._lock:
                if self._in_flight == 0:
                    break
            cancellation.sleep_interruptible(0.02)
        cancelled = 0
        with self._lock:
            stragglers = self._in_flight
        if stragglers:
            # past the deadline (or escalated by a second SIGTERM):
            # unwind survivors through the cancel machinery (bounded
            # stop beats a wedged one), then give the handler threads
            # a moment to settle their ledgers
            cancelled = self._admission.cancel_all(
                "query service drain deadline")
            settle_by = time.monotonic() + 5.0
            while time.monotonic() < settle_by:
                with self._lock:
                    if self._in_flight == 0:
                        break
                cancellation.sleep_interruptible(0.02)
        with self._lock:
            left = self._in_flight
        obs_events.emit("serve.drain", phase="end", inFlight=left,
                        connections=len(self._conns))
        return {"state": "draining", "cancelled": cancelled,
                "inFlight": left}

    def stop(self) -> None:
        """Drain, tear every connection down leak-free, and stop the
        owned session. Idempotent."""
        import signal

        if self._state == "stopped":
            return
        if self._state == "serving":
            self.drain()
        self._close_listener()
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass
        for c in conns:
            if c.thread is not None:
                c.thread.join(timeout=5.0)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._prev_sigterm is not None and \
                threading.current_thread() is threading.main_thread():
            signal.signal(signal.SIGTERM, self._prev_sigterm)
            self._prev_sigterm = None
        self._state = "stopped"
        if self._owns_session:
            self.session.stop()
        if self._admission is not None:
            # the intake valve belongs to the controller, not to this
            # daemon — reopen it so an embedder's session (tests, a
            # restarted daemon) is usable again
            self._admission.end_drain()
        global _active_daemon
        with _active_lock:
            if _active_daemon is self:
                _active_daemon = None

    def __enter__(self) -> "QueryServiceDaemon":
        return self.start() if self._state == "new" else self

    def __exit__(self, *_exc) -> bool:
        self.stop()
        return False

    def _close_listener(self) -> None:
        with self._lock:
            listener, self._listener = self._listener, None
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass

    # ---------------------------------------------------- diagnostics

    @property
    def state(self) -> str:
        return self._state

    def status(self) -> dict:
        with self._lock:
            conns = [{"tenant": c.tenant,
                      "priorityClass": c.priority_class,
                      "queries": c.queries,
                      "bytesOut": c.bytes_out}
                     for c in self._conns.values()]
            state = self._state
            in_flight = self._in_flight
        return {"state": state,
                "name": self.name,
                "port": self.port,
                "connections": conns,
                "inFlight": in_flight,
                "queriesServed": self._queries_served,
                "planCache": self.plan_cache.stats.snapshot(),
                "tenants": self.tenants.snapshot(),
                "dedupe": (self._dedupe.snapshot()
                           if self._dedupe is not None else None),
                "priorityClasses": dict(self.priority_classes)}

    def leak_report(self) -> dict:
        """All-zero after stop() — the CI leak gate."""
        with self._lock:
            threads = sum(1 for c in self._conns.values()
                          if c.thread is not None
                          and c.thread.is_alive())
            return {"connections": len(self._conns),
                    "inFlight": self._in_flight,
                    "handlerThreads": threads,
                    "listener": int(self._listener is not None)}

    # ---------------------------------------------------- accept path

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                listener = self._listener
                serving = self._state == "serving"
            if listener is None or not serving:
                return
            try:
                sock, addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return  # listener closed under us (drain/stop)
            self._admit_connection(sock, addr)

    def _admit_connection(self, sock, addr) -> None:
        with self._lock:
            if self._state != "serving" or \
                    len(self._conns) >= self.max_connections:
                full = len(self._conns) >= self.max_connections
                code = "busy" if full else "draining"
                self._refuse(sock, code)
                return
            self._conn_seq += 1
            cid = self._conn_seq
            conn = _Connection(sock, addr)
            self._conns[cid] = conn
        t = threading.Thread(target=self._serve_connection,
                             args=(cid, conn),
                             name=f"srtpu-serve-conn-{cid}",
                             daemon=True)
        conn.thread = t
        t.start()

    def _refuse(self, sock, code: str) -> None:
        obj = {"type": "error", "code": code,
               "message": f"connection refused: {code}"}
        if code in ("busy", "draining") and self.retry_after_ms > 0:
            obj["retryAfterMs"] = self.retry_after_ms
        try:
            protocol.send_json(sock, obj)
        except OSError:
            pass
        try:
            sock.close()
        except OSError:
            pass

    # ------------------------------------------------ connection path

    def _serve_connection(self, cid: int, conn: _Connection) -> None:
        from spark_rapids_tpu.obs import events as obs_events

        sock = conn.sock
        sock.settimeout(5.0)  # handshake deadline
        try:
            if not self._handshake(conn):
                return
            obs_events.emit("serve.connect", tenant=conn.tenant,
                            priorityClass=conn.priority_class,
                            addr=f"{conn.addr[0]}:{conn.addr[1]}")
            while True:
                if conn.dead:
                    return
                with self._lock:
                    if self._state == "stopped":
                        return
                try:
                    msg = protocol.recv_json(sock,
                                             self.max_frame_bytes)
                except socket.timeout:
                    continue
                except (ConnectionError, OSError):
                    return  # client went away / stop() closed us
                except protocol.ProtocolError as e:
                    self._send_error(conn, None, "protocol", str(e))
                    return
                mtype = msg.get("type")
                if mtype == "query":
                    self._handle_query(conn, msg)
                elif mtype == "cancel":
                    self._handle_cancel(conn, msg)
                elif mtype == "ping":
                    self._send(conn, {"type": "pong",
                                      "id": msg.get("id"),
                                      "state": self._state})
                elif mtype == "status":
                    # remote status snapshot — how the fleet gate
                    # reconciles billing and dedupe across replicas
                    # it can only reach over the wire
                    self._send(conn, {"type": "status_ok",
                                      "id": msg.get("id"),
                                      "status": self.status()})
                elif mtype == "bye":
                    self._send(conn, {"type": "bye_ok",
                                      "id": msg.get("id")})
                    return
                else:
                    self._send_error(conn, msg.get("id"), "protocol",
                                     f"unknown message type {mtype!r}")
        finally:
            with self._lock:
                self._conns.pop(cid, None)
            try:
                sock.close()
            except OSError:
                pass
            if conn.tenant:
                obs_events.emit("serve.disconnect", tenant=conn.tenant,
                                queries=conn.queries,
                                bytesOut=conn.bytes_out)

    def _handshake(self, conn: _Connection) -> bool:
        try:
            hello = protocol.recv_json(conn.sock, self.max_frame_bytes)
        except (ConnectionError, OSError, protocol.ProtocolError):
            return False
        if hello.get("type") != "hello":
            self._send_error(conn, hello.get("id"), "protocol",
                             "first message must be hello")
            return False
        version = int(hello.get("version", 0))
        if version > protocol.PROTOCOL_VERSION:
            self._send_error(
                conn, hello.get("id"), "protocol",
                f"protocol version {version} not supported (server "
                f"speaks {protocol.PROTOCOL_VERSION})")
            return False
        tenant = str(hello.get("tenant") or "")
        if not tenant:
            self._send_error(conn, hello.get("id"), "protocol",
                             "hello requires a tenant id")
            return False
        if ":" in tenant:
            # ':' delimits the serve:<tenant>:<class> admission
            # description the tenant-scoped cancel matches on — a
            # tenant id containing it could forge another's prefix
            self._send_error(conn, hello.get("id"), "protocol",
                             "tenant id must not contain ':'")
            return False
        pclass = str(hello.get("priorityClass") or "standard")
        if pclass not in self.priority_classes:
            self._send_error(
                conn, hello.get("id"), "protocol",
                f"unknown priority class {pclass!r}; classes: "
                f"{sorted(self.priority_classes)}")
            return False
        conn.tenant = tenant
        conn.priority_class = pclass
        conn.priority = self.priority_classes[pclass]
        conn.sock.settimeout(0.5)  # poll for stop between messages
        self._send(conn, {"type": "hello_ok", "id": hello.get("id"),
                          "version": protocol.PROTOCOL_VERSION,
                          "tenant": tenant, "priorityClass": pclass,
                          "priority": conn.priority})
        return True

    # ----------------------------------------------------- query path

    def _handle_query(self, conn: _Connection, msg: dict) -> None:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.obs import telemetry
        from spark_rapids_tpu.runtime import admission
        from spark_rapids_tpu.runtime.errors import QueryRejectedError

        mid = msg.get("id")
        tenant = conn.tenant
        entry = None
        rid = msg.get("requestId")
        if rid is not None and self._dedupe is not None:
            rid = str(rid)
            while True:
                verdict, entry = self._dedupe.claim(tenant, rid)
                if verdict == "run":
                    break  # we own the execution of this id
                if verdict == "replay":
                    # answered from the window: same result frames,
                    # no re-execution, no re-billing
                    self._replay(conn, mid, entry, "replay")
                    return
                # another handler is executing this id right now (a
                # failover resubmit raced the original): wait for its
                # outcome instead of double-executing
                if not self._await_entry(conn, entry):
                    return  # connection died / daemon stopped
                if entry.state == "done":
                    self._replay(conn, mid, entry, "joined")
                    return
                # the owner abandoned (execution failed): reclaim and
                # run it ourselves — errors stay retryable
        try:
            self.tenants.admit(tenant)
        except QueryRejectedError as e:
            if entry is not None:
                self._dedupe.abandon(entry)
                entry = None
            self._send_error(conn, mid, "tenant_quota", str(e))
            return
        with self._lock:
            self._in_flight += 1
        t0 = time.perf_counter()
        status, info, qid, payload = "error", {"planCache": "none"}, \
            None, 0
        rec, rows = None, None
        try:
            df, info, release = self.plan_cache.dataframe_for(
                self.session, tenant, msg.get("spec"),
                msg.get("params") or {})
            ok = False
            try:
                with admission.request_overrides(
                        priority=conn.priority,
                        timeout_ms=msg.get("timeoutMs"),
                        description=f"serve:{tenant}:"
                                    f"{conn.priority_class}"):
                    table = df.collect_arrow()
                ok = True
            finally:
                release(ok)
            rec = getattr(df, "_last_exec", None)
            qid = (rec or {}).get("queryId")
            status = "ok"
            rows = table.num_rows
            wall_ms = round((time.perf_counter() - t0) * 1000.0, 3)
            ipc = protocol.table_to_ipc(table)
            header = {"queryId": qid, "rows": rows,
                      "planCache": info["planCache"],
                      "wallMs": wall_ms, "payloadBytes": len(ipc)}
            # billing keys off EXECUTION, not delivery: the execution
            # completed, so the bytes bill now — a replay of this id
            # (lost ack, failover resubmit) then bills nothing, which
            # is what lets fleet billing reconcile to exactly one
            # charge per executed query
            payload = len(ipc)
            if entry is not None:
                # retain for replay BEFORE the send: if the client or
                # router dies mid-result, the resubmitted id replays
                # instead of re-executing
                self._dedupe.complete(entry, header, ipc)
                entry = None
            try:
                # lift the idle poll timeout for the send — sendall
                # treats it as a TOTAL deadline, and a large result to
                # a slow client would abort after a PARTIAL frame
                conn.sock.settimeout(None)
                protocol.send_json(conn.sock,
                                   {**header, "id": mid,
                                    "type": "result",
                                    "payload": "arrow"})
                protocol.send_frame(conn.sock, ipc)
            except OSError:
                # client vanished / stalled mid-result; a partial
                # frame desyncs the stream, so the connection closes
                conn.dead = True
                status = "error"
            else:
                conn.sock.settimeout(0.5)
                conn.queries += 1
                conn.bytes_out += payload
        except BaseException as e:
            code = protocol.error_code_for(e)
            if code in ("rejected", "draining", "device_fenced",
                        "tenant_quota"):
                status = "shed"
            elif code in ("cancelled", "deadline", "quarantined"):
                status = "cancelled"
            else:
                status = "error"
            self._send_error(conn, mid, code, str(e),
                             reason=getattr(e, "reason", None))
        finally:
            if entry is not None:
                # execution did not complete: free the slot so a
                # resubmit of this id re-runs instead of wedging
                self._dedupe.abandon(entry)
            wall_s = time.perf_counter() - t0
            hit = str(info.get("planCache", "")).startswith("hit")
            serve_rec = {
                "tenant": tenant,
                "priorityClass": conn.priority_class,
                "planCache": info.get("planCache"),
                "planCacheStats": self.plan_cache.stats.snapshot(),
            }
            if rec is not None:
                rec["serve"] = serve_rec
            if qid:
                telemetry.ledger.label_query(
                    qid, tenant=tenant,
                    priorityClass=conn.priority_class)
            self.tenants.settle(
                tenant, qid, status, wall_s=wall_s,
                telemetry=(rec or {}).get("telemetry"),
                plan_cache_hit=hit, payload_bytes=payload)
            with self._lock:
                self._in_flight -= 1
                self._queries_served += 1
            obs_events.emit(
                "serve.query", tenant=tenant,
                priorityClass=conn.priority_class,
                planCache=info.get("planCache"), status=status,
                rows=rows, wallMs=round(wall_s * 1000.0, 3))

    def _await_entry(self, conn: _Connection,
                     entry: _DedupeEntry) -> bool:
        """Wait (bounded polls) for another handler's execution of the
        same request id; False when this connection/daemon went away
        first."""
        while not entry.event.wait(timeout=0.2):
            if conn.dead:
                return False
            with self._lock:
                if self._state == "stopped":
                    return False
        return True

    def _replay(self, conn: _Connection, mid,
                entry: _DedupeEntry, outcome: str) -> None:
        """Re-send a retained result under the current message id.
        No admit, no settle: the execution already billed."""
        from spark_rapids_tpu.obs import events as obs_events

        sock = conn.sock
        try:
            sock.settimeout(None)
            protocol.send_json(sock, {**entry.header, "id": mid,
                                      "type": "result",
                                      "payload": "arrow",
                                      "dedupe": True})
            protocol.send_frame(sock, entry.payload)
            sock.settimeout(0.5)
            conn.queries += 1
        except OSError:
            conn.dead = True
        obs_events.emit("serve.dedupe", tenant=conn.tenant,
                        requestId=entry.key[1], outcome=outcome)

    def _handle_cancel(self, conn: _Connection, msg: dict) -> None:
        # cancel is TENANT-SCOPED: a connection can only unwind
        # queries its own tenant submitted — handles carry the
        # serve:<tenant>:<class> description (':' is banned in tenant
        # ids, so the prefix is unforgeable), and both the by-id and
        # the bare cancel-all form filter on it. Cross-tenant cancel
        # is an operator action: admission.get().cancel/cancel_all
        # in-process, never the wire.
        qid = msg.get("queryId")
        if qid is not None:
            try:
                qid = int(qid)
            except (TypeError, ValueError):
                self._send_error(conn, msg.get("id"), "protocol",
                                 f"bad queryId {qid!r}")
                return
        prefix = f"serve:{conn.tenant}:"
        n = self._admission.cancel_where(
            lambda h: h.description.startswith(prefix)
            and (qid is None or h.query_id == qid),
            f"cancelled by tenant {conn.tenant}")
        self._send(conn, {"type": "cancel_ok", "id": msg.get("id"),
                          "cancelled": n})

    # -------------------------------------------------------- sending

    def _send(self, conn: _Connection, obj: dict) -> None:
        """Control-frame send with the 0.5s idle poll timeout lifted:
        sendall treats a socket timeout as a TOTAL deadline, so a slow
        peer could otherwise cut a frame in half and desync the
        stream. A failed send marks the connection dead — the message
        loop closes it rather than serve a desynced client."""
        sock = conn.sock
        try:
            sock.settimeout(None)
            protocol.send_json(sock, obj)
            sock.settimeout(0.5)
        except OSError:
            conn.dead = True

    def _send_error(self, conn: _Connection, mid, code: str,
                    message: str, reason: Optional[str] = None
                    ) -> None:
        obj = {"type": "error", "id": mid, "code": code,
               "message": message}
        if reason:
            obj["reason"] = reason
        if code in ("busy", "draining") and self.retry_after_ms > 0:
            # backpressure hint: retry THIS replica no sooner than
            # this — clients sleep it, the router cools us down
            obj["retryAfterMs"] = self.retry_after_ms
        self._send(conn, obj)
