"""ReplicaSupervisor — process-per-replica fleet lifecycle.

One supervisor owns N replica daemons (serve/replica.py subprocesses),
each with its own warm session and, when `fleet.replica.mesh` > 0, its
own chip subset via the existing multichip conf. The supervisor is the
part of the fleet that makes replica death BORING:

- spawn: per-replica env (name, JSON conf, ready-file path) + `python
  -m spark_rapids_tpu.serve.replica`; readiness is the atomically
  renamed ready file carrying the ephemeral serve/http ports.
- monitor: a poll loop reaps exits. An exit while serving is a crash —
  the replica crash-loops back up under the shared backoff curve
  (fleet.restart.{backoffMs,maxBackoffMs}), up to
  fleet.restart.maxRestarts consecutive failures before `giveup`
  (a replica that came back to ready resets its crash count).
- stop: SIGTERM every replica (graceful drain inside — server.py),
  SIGKILL past fleet.drain.timeoutMs, reap everything, delete ready
  files. Bounded shutdown is a contract: the fleet gate asserts zero
  leaked processes.

`restart_replica` is the rolling-restart primitive (drain one, respawn
it, wait ready) and `kill` is the chaos primitive (the fleet gate's
kill -9). Every transition emits a `fleet.replica` event; counters
surface via stats_snapshot() -> the srtpu_fleet_supervisor_* prom
family (obs/registry.py).
"""

from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional

_active_supervisor = None
_active_lock = threading.Lock()


def active_supervisor() -> Optional["ReplicaSupervisor"]:
    """The most recently started supervisor in this process (the
    obs/registry fleet-block hook)."""
    return _active_supervisor


class _Replica:
    __slots__ = ("name", "conf", "proc", "ready_path", "generation",
                 "port", "http_port", "pid", "state", "crashes",
                 "restarts", "restart_at")

    def __init__(self, name: str, conf: dict):
        self.name = name
        self.conf = conf
        self.proc: Optional[subprocess.Popen] = None
        self.ready_path = ""
        self.generation = 0
        self.port: Optional[int] = None
        self.http_port: Optional[int] = None
        self.pid: Optional[int] = None
        # spawning | ready | restarting | giveup | stopped
        self.state = "stopped"
        self.crashes = 0          # consecutive, reset on ready
        self.restarts = 0         # lifetime
        self.restart_at = 0.0     # monotonic deadline for respawn

    def endpoint(self) -> dict:
        return {"name": self.name, "host": "127.0.0.1",
                "port": self.port, "httpPort": self.http_port,
                "pid": self.pid, "state": self.state,
                "restarts": self.restarts}


class ReplicaSupervisor:
    """Spawn/monitor/restart a fleet of replica daemons."""

    def __init__(self, conf: Optional[dict] = None,
                 replica_confs: Optional[List[dict]] = None):
        from spark_rapids_tpu.config import rapids_conf as rc
        from spark_rapids_tpu.runtime.backoff import BackoffPolicy

        self._settings = dict(conf or {})
        rconf = rc.RapidsConf(self._settings)
        self.max_restarts = rconf.get(rc.FLEET_RESTART_MAX)
        self.spawn_timeout_ms = rconf.get(rc.FLEET_SPAWN_TIMEOUT_MS)
        self.drain_timeout_ms = rconf.get(rc.FLEET_DRAIN_TIMEOUT_MS)
        self._restart_policy = BackoffPolicy(
            max(1, self.max_restarts),
            rconf.get(rc.FLEET_RESTART_BACKOFF_MS),
            rconf.get(rc.FLEET_RESTART_MAX_BACKOFF_MS))
        mesh = rconf.get(rc.FLEET_REPLICA_MESH)
        if replica_confs is None:
            n = rconf.get(rc.FLEET_REPLICAS)
            replica_confs = [dict(self._settings) for _ in range(n)]
        self._replicas: List[_Replica] = []
        for i, rcnf in enumerate(replica_confs):
            per = dict(rcnf)
            # the replica's daemon must bind its OWN ephemeral port —
            # the conf'd serve.port belongs to the router, not to N
            # replicas racing for it
            per["spark.rapids.tpu.serve.port"] = 0
            if mesh > 0 and "spark.rapids.tpu.mesh" not in per:
                per["spark.rapids.tpu.mesh"] = mesh
            self._replicas.append(_Replica(f"replica-{i}", per))
        self._dir = tempfile.mkdtemp(prefix="srtpu-fleet-")
        self._lock = threading.Lock()
        self._stopping = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._stats = {"spawns": 0, "restarts": 0, "exits": 0,
                       "giveups": 0, "kills": 0}
        self._state = "new"

    # ------------------------------------------------------ lifecycle

    def start(self) -> "ReplicaSupervisor":
        if self._state != "new":
            raise RuntimeError(f"supervisor already {self._state}")
        self._state = "running"
        for r in self._replicas:
            self._spawn(r)
        self._monitor = threading.Thread(
            target=self._monitor_loop, name="srtpu-fleet-monitor",
            daemon=True)
        self._monitor.start()
        global _active_supervisor
        with _active_lock:
            _active_supervisor = self
        return self

    def wait_ready(self, timeout_ms: Optional[int] = None,
                   min_ready: Optional[int] = None) -> List[dict]:
        """Block until `min_ready` (default: all non-giveup) replicas
        are accepting; returns their endpoints. TimeoutError past the
        spawn budget."""
        from spark_rapids_tpu.runtime import cancellation

        deadline = time.monotonic() + (
            self.spawn_timeout_ms if timeout_ms is None
            else timeout_ms) / 1000.0
        while True:
            with self._lock:
                live = [r for r in self._replicas
                        if r.state != "giveup"]
                ready = [r for r in live if r.state == "ready"]
                need = len(live) if min_ready is None \
                    else min(min_ready, len(live))
            if live and len(ready) >= need:
                return [r.endpoint() for r in ready]
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"fleet spawn: {len(ready)}/{need} replicas ready "
                    f"within {self.spawn_timeout_ms}ms")
            cancellation.sleep_interruptible(0.05)

    def stop(self) -> None:
        """SIGTERM everything (graceful drain), SIGKILL stragglers
        past fleet.drain.timeoutMs, reap, clean up. Idempotent."""
        from spark_rapids_tpu.obs import events as obs_events

        if self._state == "stopped":
            return
        self._state = "stopped"
        self._stopping.set()
        if self._monitor is not None:
            # park the monitor FIRST so no respawn races the
            # teardown into a leaked process
            self._monitor.join(timeout=5.0)
        with self._lock:
            procs = [(r, r.proc) for r in self._replicas
                     if r.proc is not None]
        obs_events.emit("fleet.drain", phase="begin",
                        replicas=len(procs))
        for _r, p in procs:
            if p.poll() is None:
                try:
                    p.send_signal(signal.SIGTERM)
                except OSError:
                    pass
        deadline = time.monotonic() + self.drain_timeout_ms / 1000.0
        for r, p in procs:
            left = max(0.05, deadline - time.monotonic())
            try:
                p.wait(timeout=left)
            except subprocess.TimeoutExpired:
                try:
                    p.kill()
                except OSError:
                    pass
                p.wait(timeout=10.0)
            r.state = "stopped"
        for r in self._replicas:
            if r.ready_path and os.path.exists(r.ready_path):
                try:
                    os.remove(r.ready_path)
                except OSError:
                    pass
        obs_events.emit("fleet.drain", phase="end",
                        replicas=len(procs))
        global _active_supervisor
        with _active_lock:
            if _active_supervisor is self:
                _active_supervisor = None

    def __enter__(self) -> "ReplicaSupervisor":
        return self.start() if self._state == "new" else self

    def __exit__(self, *_exc) -> bool:
        self.stop()
        return False

    # ------------------------------------------------------ fleet ops

    def endpoints(self) -> List[dict]:
        with self._lock:
            return [r.endpoint() for r in self._replicas
                    if r.state == "ready"]

    def kill(self, name: str, sig: int = signal.SIGKILL) -> bool:
        """Chaos/ops primitive: signal one replica by name (the fleet
        gate's kill -9 lands here). The monitor reaps and crash-loops
        it like any other death."""
        with self._lock:
            r = self._by_name(name)
            proc = r.proc if r is not None else None
        if proc is None or proc.poll() is not None:
            return False
        self._stats["kills"] += 1
        try:
            proc.send_signal(sig)
        except OSError:
            return False
        return True

    def restart_replica(self, name: str,
                        timeout_ms: Optional[int] = None) -> dict:
        """Rolling-restart primitive: drain one replica (SIGTERM),
        reap it, respawn it, wait for its ready file. Returns the new
        endpoint. The caller restarts replicas ONE at a time so the
        fleet never loses more than one member of capacity."""
        from spark_rapids_tpu.runtime import cancellation

        with self._lock:
            r = self._by_name(name)
            if r is None:
                raise KeyError(f"unknown replica {name!r}")
            proc = r.proc
            r.state = "restarting"
        if proc is not None and proc.poll() is None:
            try:
                proc.send_signal(signal.SIGTERM)
            except OSError:
                pass
            try:
                proc.wait(timeout=self.drain_timeout_ms / 1000.0)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait(timeout=10.0)
        with self._lock:
            self._stats["restarts"] += 1
            r.restarts += 1
            r.crashes = 0  # operator-intended, not a crash loop
            self._spawn_locked(r)
        deadline = time.monotonic() + (
            self.spawn_timeout_ms if timeout_ms is None
            else timeout_ms) / 1000.0
        while time.monotonic() < deadline:
            with self._lock:
                if r.state == "ready":
                    return r.endpoint()
            cancellation.sleep_interruptible(0.05)
        raise TimeoutError(f"replica {name!r} did not come back ready")

    def stats_snapshot(self) -> dict:
        with self._lock:
            states = [r.state for r in self._replicas]
            return {**self._stats,
                    "replicas": len(self._replicas),
                    "ready": states.count("ready"),
                    "giveup": states.count("giveup")}

    # ------------------------------------------------------ internals

    def _by_name(self, name: str) -> Optional[_Replica]:
        for r in self._replicas:
            if r.name == name:
                return r
        return None

    def _spawn(self, r: _Replica) -> None:
        with self._lock:
            self._spawn_locked(r)

    def _spawn_locked(self, r: _Replica) -> None:
        from spark_rapids_tpu.obs import events as obs_events

        r.generation += 1
        r.ready_path = os.path.join(
            self._dir, f"ready-{r.name}-{r.generation}.json")
        env = dict(os.environ)
        env["SRTPU_REPLICA_NAME"] = r.name
        env["SRTPU_REPLICA_CONF"] = json.dumps(r.conf)
        env["SRTPU_REPLICA_READY"] = r.ready_path
        # the replica runs with cwd in the fleet scratch dir — make
        # sure the package stays importable from a repo checkout
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"]
            if env.get("PYTHONPATH") else "")
        r.proc = subprocess.Popen(
            [sys.executable, "-m", "spark_rapids_tpu.serve.replica"],
            env=env, cwd=self._dir,
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL)
        r.pid = r.proc.pid
        r.port = None
        r.http_port = None
        r.state = "spawning"
        self._stats["spawns"] += 1
        obs_events.emit("fleet.replica", name=r.name, phase="spawn",
                        pid=r.pid, port=None, restarts=r.restarts)

    def _monitor_loop(self) -> None:
        from spark_rapids_tpu.obs import events as obs_events

        while not self._stopping.wait(timeout=0.05):
            now = time.monotonic()
            with self._lock:
                replicas = list(self._replicas)
            for r in replicas:
                with self._lock:
                    state, proc = r.state, r.proc
                if state == "spawning" and \
                        os.path.exists(r.ready_path):
                    try:
                        with open(r.ready_path) as f:
                            info = json.load(f)
                    except (OSError, ValueError):
                        continue  # racing the atomic rename
                    with self._lock:
                        r.port = info.get("port")
                        r.http_port = info.get("httpPort")
                        r.state = "ready"
                        r.crashes = 0
                    obs_events.emit(
                        "fleet.replica", name=r.name, phase="ready",
                        pid=r.pid, port=r.port, restarts=r.restarts)
                    continue
                if state in ("spawning", "ready") and \
                        proc is not None and proc.poll() is not None:
                    # died under us: crash-loop it back up
                    self._stats["exits"] += 1
                    obs_events.emit(
                        "fleet.replica", name=r.name, phase="exit",
                        pid=r.pid, port=r.port, restarts=r.restarts)
                    with self._lock:
                        r.crashes += 1
                        r.port = None
                        r.http_port = None
                        if self.max_restarts <= 0 or \
                                r.crashes > self.max_restarts:
                            r.state = "giveup"
                            self._stats["giveups"] += 1
                        else:
                            r.state = "restarting"
                            r.restart_at = now + \
                                self._restart_policy.delay_s(
                                    r.crashes - 1)
                    if r.state == "giveup":
                        obs_events.emit(
                            "fleet.replica", name=r.name,
                            phase="giveup", pid=r.pid, port=None,
                            restarts=r.restarts)
                    continue
                if state == "restarting" and r.restart_at and \
                        now >= r.restart_at:
                    with self._lock:
                        r.restart_at = 0.0
                        self._stats["restarts"] += 1
                        r.restarts += 1
                        self._spawn_locked(r)
                    obs_events.emit(
                        "fleet.replica", name=r.name, phase="restart",
                        pid=r.pid, port=None, restarts=r.restarts)
