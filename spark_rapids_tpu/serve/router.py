"""FleetRouter — the health-routed front door of the serving fleet.

The router is a daemon speaking the SAME length-prefixed protocol as a
replica (serve/protocol.py): existing clients point at it unchanged.
Behind it, every `query` message is routed across the replica set:

- **Health-gated**: a poll loop samples each replica's /readyz (the
  obs/http endpoint, whose body carries the admission `load` shed
  signal), falling back to a TCP probe when a replica exposes no HTTP
  port. fleet.health.maxConsecutiveFailures failed probes route
  around a replica; a dead one is also discovered synchronously by a
  failed send, so the poll interval bounds STALENESS, not failover
  latency.
- **Affinity-routed**: the hash-ring input is
  plan_cache.affinity_key(tenant, spec, params) — the structural
  identity minus conf and literal values — rendezvous-hashed over the
  routable replicas, so repeat shapes land on the replica whose plan
  cache already holds their template. Ties and fallbacks go to the
  least-loaded routable replica.
- **Idempotent failover**: every routed request carries a requestId
  (client-supplied or router-minted). A replica dying mid-query
  (connection break) or refusing with busy/draining/device_fenced
  consumes one of fleet.failover.maxAttempts and the SAME requestId
  resubmits to the next candidate — the replica-side dedupe window
  (server.py) makes the retry exactly-once: re-execute if the first
  never finished, replay if only the ack was lost. busy/draining
  refusals also cool the replica down for its retryAfterMs hint.
  When every attempt is spent the client gets a clean `unavailable`
  error frame, never a hang.

The router holds per-client-connection backend sockets (hello'd with
the client's tenant/priorityClass, so replica-side tenant governance
sees the true tenant), relays result frames verbatim (no Arrow
re-parse on the hot path), forwards `cancel` to every replica the
client touched, and exposes /healthz + aggregated /readyz + /metrics
via obs/http.FleetHttpServer. Counters surface via stats_snapshot()
-> the srtpu_fleet_router_* prom family.
"""

from __future__ import annotations

import hashlib
import itertools
import socket
import threading
import time
import uuid
from typing import Dict, List, Optional

from spark_rapids_tpu.serve import protocol

_active_router = None
_active_lock = threading.Lock()


def active_router() -> Optional["FleetRouter"]:
    """The most recently started router in this process (the
    obs/registry fleet-block hook)."""
    return _active_router


class _Member:
    """One replica as the router sees it."""

    __slots__ = ("name", "host", "port", "http_port", "ready",
                 "failures", "cooldown_until", "load", "routed")

    def __init__(self, name: str, host: str, port: int,
                 http_port: Optional[int]):
        self.name = name
        self.host = host
        self.port = port
        self.http_port = http_port
        self.ready = True  # optimistic until a probe says otherwise
        self.failures = 0
        self.cooldown_until = 0.0
        self.load: dict = {}
        self.routed = 0

    def snapshot(self) -> dict:
        return {"host": self.host, "port": self.port,
                "httpPort": self.http_port, "ready": self.ready,
                "consecutiveFailures": self.failures,
                "coolingDown": self.cooldown_until > time.monotonic(),
                "load": self.load, "routed": self.routed}


class _ClientConn:
    """One accepted client and its hello'd backend sockets."""

    __slots__ = ("sock", "addr", "tenant", "priority_class",
                 "backends", "dead", "thread")

    def __init__(self, sock, addr):
        self.sock = sock
        self.addr = addr
        self.tenant = ""
        self.priority_class = "standard"
        self.backends: Dict[str, socket.socket] = {}
        self.dead = False
        self.thread: Optional[threading.Thread] = None


class FleetRouter:
    """Front-door daemon load-balancing a replica fleet."""

    def __init__(self, endpoints: Optional[List[dict]] = None,
                 supervisor=None, conf: Optional[dict] = None):
        from spark_rapids_tpu.config import rapids_conf as rc

        rconf = rc.RapidsConf(dict(conf or {}))
        self.host = rconf.get(rc.FLEET_ROUTER_HOST)
        self._conf_port = rconf.get(rc.FLEET_ROUTER_PORT)
        self._http_port_conf = rconf.get(rc.FLEET_ROUTER_HTTP_PORT)
        self.max_frame_bytes = rconf.get(rc.SERVE_MAX_FRAME_BYTES)
        self.retry_after_ms = rconf.get(rc.SERVE_RETRY_AFTER_MS)
        self.health_interval_ms = rconf.get(rc.FLEET_HEALTH_INTERVAL_MS)
        self.max_health_failures = rconf.get(rc.FLEET_HEALTH_MAX_FAILURES)
        self.max_attempts = rconf.get(rc.FLEET_FAILOVER_ATTEMPTS)
        self._supervisor = supervisor
        self._members: Dict[str, _Member] = {}
        for ep in (endpoints or []):
            self._add_member(ep)
        self.port: Optional[int] = None
        self.http_port: Optional[int] = None
        self._http = None
        self._listener: Optional[socket.socket] = None
        self._accept_thread: Optional[threading.Thread] = None
        self._health_thread: Optional[threading.Thread] = None
        self._stop_evt = threading.Event()
        self._lock = threading.Lock()
        self._conns: Dict[int, _ClientConn] = {}
        self._conn_seq = 0
        self._state = "new"
        self._rid_base = uuid.uuid4().hex[:12]
        self._rid_counter = itertools.count(1)
        self._stats = {"queriesRouted": 0, "failovers": 0,
                       "rerouted": 0, "unavailable": 0,
                       "mintedRequestIds": 0, "replays": 0}

    # ------------------------------------------------------ lifecycle

    def start(self) -> "FleetRouter":
        from spark_rapids_tpu.obs.http import FleetHttpServer

        if self._state != "new":
            raise RuntimeError(f"router already {self._state}")
        self._listener = socket.socket(socket.AF_INET,
                                       socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET,
                                  socket.SO_REUSEADDR, 1)
        self._listener.bind((self.host, int(self._conf_port)))
        self._listener.listen(128)
        self._listener.settimeout(0.2)
        self.port = self._listener.getsockname()[1]
        self._state = "serving"
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="srtpu-fleet-accept",
            daemon=True)
        self._accept_thread.start()
        self._health_thread = threading.Thread(
            target=self._health_loop, name="srtpu-fleet-health",
            daemon=True)
        self._health_thread.start()
        try:
            self._http = FleetHttpServer(self,
                                         port=self._http_port_conf)
            self.http_port = self._http.port
        except OSError:
            self._http = None
        global _active_router
        with _active_lock:
            _active_router = self
        return self

    def stop(self) -> None:
        if self._state == "stopped":
            return
        self._state = "stopped"
        self._stop_evt.set()
        listener, self._listener = self._listener, None
        if listener is not None:
            try:
                listener.close()
            except OSError:
                pass
        with self._lock:
            conns = list(self._conns.values())
        for c in conns:
            for b in list(c.backends.values()):
                try:
                    b.close()
                except OSError:
                    pass
            try:
                c.sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                c.sock.close()
            except OSError:
                pass
        for c in conns:
            if c.thread is not None:
                c.thread.join(timeout=5.0)
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        if self._health_thread is not None:
            self._health_thread.join(timeout=5.0)
        if self._http is not None:
            self._http.close()
            self._http = None
        global _active_router
        with _active_lock:
            if _active_router is self:
                _active_router = None

    def __enter__(self) -> "FleetRouter":
        return self.start() if self._state == "new" else self

    def __exit__(self, *_exc) -> bool:
        self.stop()
        return False

    # ---------------------------------------------------- diagnostics

    def health(self) -> dict:
        """The aggregated readiness body FleetHttpServer serves:
        ready while >= 1 replica is routable."""
        now = time.monotonic()
        with self._lock:
            members = {n: m.snapshot()
                       for n, m in self._members.items()}
            routable = [n for n, m in self._members.items()
                        if self._routable(m, now)]
        return {"ready": bool(routable),
                "routable": sorted(routable),
                "replicas": members}

    def stats_snapshot(self) -> dict:
        now = time.monotonic()
        with self._lock:
            routable = sum(1 for m in self._members.values()
                           if self._routable(m, now))
            return {**self._stats,
                    "replicas": len(self._members),
                    "routable": routable,
                    "connections": len(self._conns)}

    def leak_report(self) -> dict:
        with self._lock:
            threads = sum(1 for c in self._conns.values()
                          if c.thread is not None
                          and c.thread.is_alive())
            return {"connections": len(self._conns),
                    "handlerThreads": threads,
                    "listener": int(self._listener is not None)}

    # ------------------------------------------------------ membership

    def _add_member(self, ep: dict) -> None:
        self._members[ep["name"]] = _Member(
            ep["name"], ep.get("host", "127.0.0.1"),
            int(ep["port"]), ep.get("httpPort"))

    def _refresh_members(self) -> None:
        """Fold the supervisor's current endpoints in: restarted
        replicas come back on NEW ports; gone replicas drop."""
        if self._supervisor is None:
            return
        eps = {ep["name"]: ep for ep in self._supervisor.endpoints()}
        with self._lock:
            for name, ep in eps.items():
                m = self._members.get(name)
                if m is None:
                    self._add_member(ep)
                elif (m.host, m.port) != (ep.get("host", "127.0.0.1"),
                                          int(ep["port"])):
                    self._add_member(ep)  # replaces: fresh state
            for name in list(self._members):
                if name not in eps:
                    del self._members[name]

    def _routable(self, m: _Member, now: float) -> bool:
        return m.ready and m.cooldown_until <= now

    def _candidates(self, affinity: str) -> List[str]:
        """Routable replica names, affinity-ranked: rendezvous hash
        (highest-random-weight) of the affinity key over the members,
        so a repeat spec consistently prefers the same replica while
        every spec still spreads across the fleet; equal-rank fallback
        order is by reported load."""
        now = time.monotonic()
        with self._lock:
            live = [(n, m) for n, m in self._members.items()
                    if self._routable(m, now)]

        def rank(item):
            name, m = item
            w = hashlib.sha256(
                f"{affinity}|{name}".encode()).hexdigest()
            return w

        def load_of(m: _Member) -> int:
            return int(m.load.get("running", 0)) + \
                int(m.load.get("queued", 0))

        ranked = sorted(live, key=rank, reverse=True)
        if len(ranked) > 1:
            # affinity picks the head; the FALLBACK order (failover
            # targets) prefers the least-loaded survivors
            head, rest = ranked[0], ranked[1:]
            rest.sort(key=lambda it: load_of(it[1]))
            ranked = [head] + rest
        return [n for n, _m in ranked]

    def _mark_dead(self, name: str) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.ready = False
                m.failures = max(m.failures,
                                 self.max_health_failures)

    def _cooldown(self, name: str, ms: int) -> None:
        with self._lock:
            m = self._members.get(name)
            if m is not None:
                m.cooldown_until = max(
                    m.cooldown_until,
                    time.monotonic() + max(0, ms) / 1000.0)

    # ---------------------------------------------------- health loop

    def _health_loop(self) -> None:
        from spark_rapids_tpu.obs import events as obs_events

        interval = max(0.01, self.health_interval_ms / 1000.0)
        while not self._stop_evt.wait(timeout=interval):
            self._refresh_members()
            with self._lock:
                members = list(self._members.items())
            for name, m in members:
                ready, load = self._probe(m)
                with self._lock:
                    cur = self._members.get(name)
                    if cur is not m:
                        continue  # replaced mid-probe
                    was = m.ready
                    if ready:
                        m.failures = 0
                        m.ready = True
                        m.load = load or m.load
                    else:
                        m.failures += 1
                        if m.failures >= self.max_health_failures:
                            m.ready = False
                    flipped = was != m.ready
                if flipped:
                    obs_events.emit("fleet.health", replica=name,
                                    ready=m.ready,
                                    consecutiveFailures=m.failures)

    def _probe(self, m: _Member):
        """(ready, load) for one member: /readyz when it has an HTTP
        port (503 -> not ready; body carries the shed signal), else a
        bare TCP connect to the serve port."""
        if m.http_port:
            import json
            import urllib.error
            import urllib.request

            try:
                with urllib.request.urlopen(
                        f"http://{m.host}:{m.http_port}/readyz",
                        timeout=1.0) as resp:
                    body = json.loads(resp.read().decode())
                    return True, body.get("load") or {}
            except urllib.error.HTTPError as e:
                if e.code == 503:
                    try:
                        body = json.loads(e.read().decode())
                        return False, body.get("load") or {}
                    except (ValueError, OSError):
                        return False, {}
                return False, {}
            except (OSError, ValueError):
                return False, {}
        try:
            s = socket.create_connection((m.host, m.port),
                                         timeout=1.0)
            s.close()
            return True, {}
        except OSError:
            return False, {}

    # ---------------------------------------------------- accept path

    def _accept_loop(self) -> None:
        while True:
            with self._lock:
                listener = self._listener
                serving = self._state == "serving"
            if listener is None or not serving:
                return
            try:
                sock, addr = listener.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            with self._lock:
                self._conn_seq += 1
                cid = self._conn_seq
                conn = _ClientConn(sock, addr)
                self._conns[cid] = conn
            t = threading.Thread(target=self._serve_client,
                                 args=(cid, conn),
                                 name=f"srtpu-fleet-conn-{cid}",
                                 daemon=True)
            conn.thread = t
            t.start()

    # ------------------------------------------------- client session

    def _serve_client(self, cid: int, conn: _ClientConn) -> None:
        sock = conn.sock
        sock.settimeout(5.0)
        try:
            if not self._client_hello(conn):
                return
            sock.settimeout(0.5)
            while True:
                if conn.dead:
                    return
                if self._state != "serving":
                    return
                try:
                    msg = protocol.recv_json(sock,
                                             self.max_frame_bytes)
                except socket.timeout:
                    continue
                except (ConnectionError, OSError):
                    return
                except protocol.ProtocolError as e:
                    self._send(conn, {"type": "error", "id": None,
                                      "code": "protocol",
                                      "message": str(e)})
                    return
                mtype = msg.get("type")
                if mtype == "query":
                    self._route_query(conn, msg)
                elif mtype == "cancel":
                    self._route_cancel(conn, msg)
                elif mtype == "ping":
                    self._send(conn, {"type": "pong",
                                      "id": msg.get("id"),
                                      "state": self._state,
                                      "router": True})
                elif mtype == "status":
                    self._send(conn, {"type": "status_ok",
                                      "id": msg.get("id"),
                                      "status": {
                                          "router": self.health(),
                                          "stats":
                                              self.stats_snapshot()}})
                elif mtype == "bye":
                    self._send(conn, {"type": "bye_ok",
                                      "id": msg.get("id")})
                    return
                else:
                    self._send(conn, {
                        "type": "error", "id": msg.get("id"),
                        "code": "protocol",
                        "message": f"unknown message type {mtype!r}"})
        finally:
            for name, b in list(conn.backends.items()):
                try:
                    protocol.send_json(b, {"type": "bye", "id": 0})
                except OSError:
                    pass
                try:
                    b.close()
                except OSError:
                    pass
            conn.backends.clear()
            with self._lock:
                self._conns.pop(cid, None)
            try:
                conn.sock.close()
            except OSError:
                pass

    def _client_hello(self, conn: _ClientConn) -> bool:
        """Terminate the hello at the router: tenant/class bind here
        and re-play against each backend the client's queries touch.
        Validation of the class itself is deferred to the first
        backend hello (the router doesn't know the classes)."""
        try:
            hello = protocol.recv_json(conn.sock,
                                       self.max_frame_bytes)
        except (ConnectionError, OSError, protocol.ProtocolError):
            return False
        mid = hello.get("id")
        if hello.get("type") != "hello":
            self._send(conn, {"type": "error", "id": mid,
                              "code": "protocol",
                              "message": "first message must be hello"})
            return False
        version = int(hello.get("version", 0))
        if version > protocol.PROTOCOL_VERSION:
            self._send(conn, {
                "type": "error", "id": mid, "code": "protocol",
                "message": f"protocol version {version} not supported "
                           f"(router speaks "
                           f"{protocol.PROTOCOL_VERSION})"})
            return False
        tenant = str(hello.get("tenant") or "")
        if not tenant or ":" in tenant:
            self._send(conn, {"type": "error", "id": mid,
                              "code": "protocol",
                              "message": "hello requires a tenant id "
                                         "without ':'"})
            return False
        conn.tenant = tenant
        conn.priority_class = str(hello.get("priorityClass")
                                  or "standard")
        # bind a first backend NOW so a bad priority class (or an
        # unavailable fleet) fails the handshake exactly like the
        # single-daemon path would
        names = self._candidates(tenant)
        reply = None
        for name in names[:self.max_attempts]:
            try:
                sock, reply = self._backend_hello(conn, name)
            except (ConnectionError, OSError):
                self._mark_dead(name)
                continue
            if reply.get("type") == "hello_ok":
                conn.backends[name] = sock
                self._send(conn, {**reply, "id": mid})
                return True
            break  # a clean refusal/validation error: relay it
        if reply is not None:
            self._send(conn, {**reply, "id": mid})
        else:
            self._send_unavailable(conn, mid)
        return False

    def _backend_hello(self, conn: _ClientConn, name: str):
        with self._lock:
            m = self._members.get(name)
        if m is None:
            raise ConnectionError(f"no member {name}")
        sock = socket.create_connection((m.host, m.port), timeout=5.0)
        try:
            sock.settimeout(None)
            protocol.send_json(sock, {
                "type": "hello", "id": 0,
                "version": protocol.PROTOCOL_VERSION,
                "tenant": conn.tenant,
                "priorityClass": conn.priority_class})
            reply = protocol.recv_json(sock, self.max_frame_bytes)
        except BaseException:
            try:
                sock.close()
            except OSError:
                pass
            raise
        if reply.get("type") != "hello_ok":
            try:
                sock.close()
            except OSError:
                pass
        return sock, reply

    def _backend_for(self, conn: _ClientConn, name: str):
        sock = conn.backends.get(name)
        if sock is not None:
            return sock
        sock, reply = self._backend_hello(conn, name)
        if reply.get("type") != "hello_ok":
            # governance refusal at hello time (e.g. draining):
            # surface it like a refused query so failover handles it
            raise _BackendRefused(reply)
        conn.backends[name] = sock
        return sock

    # ----------------------------------------------------- query path

    def _route_query(self, conn: _ClientConn, msg: dict) -> None:
        from spark_rapids_tpu.obs import events as obs_events
        from spark_rapids_tpu.runtime import backoff, cancellation
        from spark_rapids_tpu.serve.plan_cache import affinity_key

        mid = msg.get("id")
        rid = msg.get("requestId")
        if rid is None:
            # mint the idempotency key that makes failover resubmits
            # exactly-once against the replica dedupe windows
            rid = f"rt-{self._rid_base}-{next(self._rid_counter)}"
            self._stats["mintedRequestIds"] += 1
        msg = {**msg, "requestId": str(rid)}
        try:
            akey = affinity_key(conn.tenant, msg.get("spec"),
                                msg.get("params") or {})
        except Exception:
            # a spec the normalizer rejects still routes (the replica
            # will answer bad_spec with the real diagnostic)
            akey = conn.tenant
        last_refusal: Optional[dict] = None
        attempted: set = set()
        prev_name: Optional[str] = None
        for attempt in range(self.max_attempts):
            names = [n for n in self._candidates(akey)
                     if n not in attempted]
            if not names:
                # nothing routable right now: honor the last refusal's
                # retryAfterMs (or one default beat) before giving up,
                # instead of hot-spinning or failing early
                hint = int((last_refusal or {}).get("retryAfterMs")
                           or self.retry_after_ms or 100)
                cancellation.sleep_interruptible(
                    min(hint, 1000) / 1000.0)
                attempted.clear()
                names = [n for n in self._candidates(akey)]
                if not names:
                    break
            name = names[0]
            attempted.add(name)
            if attempt:
                self._stats["failovers"] += 1
                backoff.record_retry("fleet.failover")
                obs_events.emit(
                    "fleet.failover", requestId=str(rid),
                    tenant=conn.tenant, fromReplica=prev_name,
                    toReplica=name,
                    reason=(last_refusal or {}).get("code",
                                                    "connection"))
            prev_name = name
            try:
                sock = self._backend_for(conn, name)
            except _BackendRefused as e:
                last_refusal = e.reply
                self._note_refusal(name, e.reply)
                continue
            except (ConnectionError, OSError):
                self._mark_dead(name)
                last_refusal = None
                continue
            try:
                protocol.send_json(sock, msg)
                header = protocol.recv_json(sock,
                                            self.max_frame_bytes)
                payload = None
                if header.get("payload") == "arrow":
                    payload = protocol.recv_frame(
                        sock, self.max_frame_bytes)
            except (ConnectionError, OSError,
                    protocol.ProtocolError):
                # replica died (or desynced) mid-query: drop the
                # backend, resubmit the SAME requestId to a survivor —
                # its dedupe window guarantees single execution
                conn.backends.pop(name, None)
                try:
                    sock.close()
                except OSError:
                    pass
                self._mark_dead(name)
                last_refusal = None
                continue
            code = header.get("code")
            if header.get("type") == "error" and \
                    code in ("busy", "draining", "device_fenced"):
                # transparent reroute: the refusal cools this replica
                # down and the request moves on
                self._stats["rerouted"] += 1
                last_refusal = header
                self._note_refusal(name, header)
                continue
            with self._lock:
                m = self._members.get(name)
                if m is not None:
                    m.routed += 1
            self._stats["queriesRouted"] += 1
            if header.get("dedupe"):
                self._stats["replays"] += 1
            self._relay(conn, {**header, "id": mid,
                               "requestId": str(rid),
                               "replica": name}, payload)
            return
        self._stats["unavailable"] += 1
        self._send_unavailable(conn, mid, last_refusal)

    def _note_refusal(self, name: str, header: dict) -> None:
        hint = int(header.get("retryAfterMs")
                   or self.retry_after_ms or 0)
        if header.get("code") == "device_fenced" and \
                not header.get("retryAfterMs"):
            # fences clear on recovery, not on a client's beat —
            # poll-scale cooldown, not a single retryAfter
            hint = max(hint, self.health_interval_ms * 2)
        self._cooldown(name, hint)

    def _route_cancel(self, conn: _ClientConn, msg: dict) -> None:
        """Fan the (tenant-scoped) cancel out to every replica this
        client has touched; the summed count comes back."""
        mid = msg.get("id")
        total = 0
        for name, sock in list(conn.backends.items()):
            try:
                protocol.send_json(sock, {**msg, "id": 0})
                reply = protocol.recv_json(sock,
                                           self.max_frame_bytes)
                total += int(reply.get("cancelled", 0))
            except (ConnectionError, OSError,
                    protocol.ProtocolError):
                conn.backends.pop(name, None)
                try:
                    sock.close()
                except OSError:
                    pass
                self._mark_dead(name)
        self._send(conn, {"type": "cancel_ok", "id": mid,
                          "cancelled": total})

    # -------------------------------------------------------- sending

    def _relay(self, conn: _ClientConn, header: dict,
               payload: Optional[bytes]) -> None:
        sock = conn.sock
        try:
            sock.settimeout(None)
            protocol.send_json(sock, header)
            if payload is not None:
                protocol.send_frame(sock, payload)
            sock.settimeout(0.5)
        except OSError:
            conn.dead = True

    def _send(self, conn: _ClientConn, obj: dict) -> None:
        sock = conn.sock
        try:
            sock.settimeout(None)
            protocol.send_json(sock, obj)
            sock.settimeout(0.5)
        except OSError:
            conn.dead = True

    def _send_unavailable(self, conn: _ClientConn, mid,
                          last_refusal: Optional[dict] = None) -> None:
        obj = {"type": "error", "id": mid, "code": "unavailable",
               "message": "no routable replica (fleet exhausted "
                          "failover attempts)"}
        if last_refusal is not None:
            obj["message"] += \
                f"; last refusal: {last_refusal.get('code')}"
        if self.retry_after_ms > 0:
            obj["retryAfterMs"] = self.retry_after_ms
        self._send(conn, obj)


class _BackendRefused(Exception):
    """A backend hello answered with a governance refusal frame."""

    def __init__(self, reply: dict):
        super().__init__(reply.get("message", ""))
        self.reply = reply
