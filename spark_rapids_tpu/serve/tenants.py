"""Per-tenant quota ledgers and billing.

The transfer ledger (obs/telemetry.py) accounts every byte a query
moves and the admission controller (runtime/admission.py) governs
global concurrency; this module rolls both up PER TENANT — the unit a
multi-tenant service bills and caps. Each tenant accumulates:

- `queries` / `sheds` / `cancelled` / `errors` — outcome counts
- `bytesMovedTotal` — billed bytes, summed from each query's
  transfer-ledger summary (so billing reconciles exactly with
  telemetry.ledger.recent_query_summaries by query id)
- `deviceSeconds` — wall seconds of admitted execution
- `payloadBytesOut` — Arrow result bytes sent over the wire
- `planCacheHits` — served from the structural plan cache

Caps are enforced at `admit()` — BEFORE the query touches the
admission queue — with QueryRejectedError(reason="tenant quota"), so
one tenant's burst degrades its own traffic, never the fleet's:

- serve.tenant.maxConcurrentQueries: in-flight queries per tenant
- serve.tenant.maxDeviceBytes: cumulative billed-byte budget
"""

from __future__ import annotations

import threading
from collections import deque
from typing import Dict, Optional

from spark_rapids_tpu.runtime.errors import QueryRejectedError


class _Tenant:
    __slots__ = ("active", "queries", "sheds", "cancelled", "errors",
                 "bytes_moved", "device_seconds", "payload_out",
                 "plan_cache_hits", "query_ids")

    def __init__(self):
        self.active = 0
        self.queries = 0
        self.sheds = 0
        self.cancelled = 0
        self.errors = 0
        self.bytes_moved = 0
        self.device_seconds = 0.0
        self.payload_out = 0
        self.plan_cache_hits = 0
        self.query_ids: deque = deque(maxlen=1024)


class TenantLedger:
    """Quota + billing ledger for one daemon's tenants."""

    def __init__(self, max_concurrent: int = 0,
                 max_device_bytes: int = 0):
        self.max_concurrent = max(0, int(max_concurrent))
        self.max_device_bytes = max(0, int(max_device_bytes))
        self._lock = threading.Lock()
        self._tenants: Dict[str, _Tenant] = {}

    def _get(self, tenant: str) -> _Tenant:
        t = self._tenants.get(tenant)
        if t is None:
            t = self._tenants[tenant] = _Tenant()
        return t

    # --- quota gate ---

    def admit(self, tenant: str) -> None:
        """Take one in-flight slot for `tenant` or shed with
        reason='tenant quota'. Call `settle` exactly once after."""
        from spark_rapids_tpu.obs import events as obs_events

        with self._lock:
            t = self._get(tenant)
            if self.max_concurrent and t.active >= self.max_concurrent:
                t.sheds += 1
                obs_events.emit("serve.shed", tenant=tenant,
                                reason="tenant quota")
                raise QueryRejectedError(
                    f"tenant {tenant!r} at its concurrent-query cap "
                    f"({t.active}/{self.max_concurrent}, "
                    f"serve.tenant.maxConcurrentQueries)",
                    reason="tenant quota")
            if self.max_device_bytes and \
                    t.bytes_moved >= self.max_device_bytes:
                t.sheds += 1
                obs_events.emit("serve.shed", tenant=tenant,
                                reason="tenant quota")
                raise QueryRejectedError(
                    f"tenant {tenant!r} exhausted its device-byte "
                    f"budget ({t.bytes_moved}/{self.max_device_bytes} "
                    f"bytes billed, serve.tenant.maxDeviceBytes)",
                    reason="tenant quota")
            t.active += 1

    def record_shed(self, tenant: str) -> None:
        """An admission-layer shed (queue full / draining / fence)
        after `admit` — settle() with status='shed' does this; this
        helper covers sheds that never reached admit."""
        with self._lock:
            self._get(tenant).sheds += 1

    # --- billing ---

    def settle(self, tenant: str, query_id: Optional[int],
               status: str, wall_s: float = 0.0,
               telemetry: Optional[dict] = None,
               plan_cache_hit: bool = False,
               payload_bytes: int = 0) -> None:
        """Release the in-flight slot and bill the query.
        `status`: ok | error | cancelled | shed."""
        moved = 0
        if telemetry:
            moved = int(telemetry.get("bytesMovedTotal", 0) or 0)
        with self._lock:
            t = self._get(tenant)
            t.active = max(0, t.active - 1)
            if status == "ok":
                t.queries += 1
            elif status == "cancelled":
                t.cancelled += 1
            elif status == "shed":
                t.sheds += 1
            else:
                t.errors += 1
            t.bytes_moved += moved
            t.device_seconds += max(0.0, wall_s)
            t.payload_out += max(0, int(payload_bytes))
            if plan_cache_hit:
                t.plan_cache_hits += 1
            if query_id:
                t.query_ids.append(query_id)

    def reset_usage(self, tenant: str) -> None:
        """Zero a tenant's billed-byte budget consumption (the
        operator's quota-reset lever; counts stay)."""
        with self._lock:
            self._get(tenant).bytes_moved = 0

    # --- views ---

    def query_ids(self, tenant: str) -> list:
        with self._lock:
            t = self._tenants.get(tenant)
            return list(t.query_ids) if t else []

    def snapshot(self) -> Dict[str, dict]:
        """Numeric per-tenant billing view (daemon.status(), the
        registry, and /queries)."""
        with self._lock:
            return {
                name: {
                    "active": t.active,
                    "queries": t.queries,
                    "sheds": t.sheds,
                    "cancelled": t.cancelled,
                    "errors": t.errors,
                    "bytesMovedTotal": t.bytes_moved,
                    "deviceSeconds": round(t.device_seconds, 3),
                    "payloadBytesOut": t.payload_out,
                    "planCacheHits": t.plan_cache_hits,
                } for name, t in sorted(self._tenants.items())}
