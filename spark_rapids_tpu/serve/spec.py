"""JSON query-spec DSL -> DataFrame compiler.

The engine has no SQL front-end; served queries arrive as a small
JSON relational algebra instead — close enough to a logical plan that
compilation is a direct fold onto the DataFrame API, and regular
enough that the plan cache (serve/plan_cache.py) can canonicalize a
spec and parameterize its literals out by a plain tree walk.

Relations (`{"op": ...}` nodes):
  {"op": "parquet", "path": "<path or [paths]>"}
  {"op": "range", "start": 0, "end": N, "step": 1}
  {"op": "filter", "input": R, "cond": E}
  {"op": "select", "input": R, "cols": ["a", {"expr": E, "as": "x"}]}
  {"op": "agg", "input": R, "groupBy": ["k", ...],
   "aggs": [{"fn": "sum", "col": "v", "as": "total"}, ...]}
  {"op": "join", "left": R, "right": R, "on": ["k"], "how": "inner"}
  {"op": "orderBy", "input": R,
   "keys": [{"col": "k", "asc": true}, ...]}
  {"op": "limit", "input": R, "n": 10}

Expressions:
  {"col": "name"}            column reference
  {"lit": value}             literal (parameterized out by the cache)
  {"param": "name"}          named parameter, bound per request
  {"fn": "<op>", "args": [E, ...]}   operators/functions (FNS below)

Parameters make repeated traffic cacheable BY CONSTRUCTION: a
dashboard sends the same spec with different `params` bindings and
the serving layer recognizes the shape. `{"lit": ...}` is still
normalized to an auto-parameter, so even literal-embedding clients
hit the cache.
"""

from __future__ import annotations

from typing import Dict, List

from spark_rapids_tpu.api import functions as F
from spark_rapids_tpu.api.column import Column


class SpecError(ValueError):
    """A query spec that cannot compile — wire code `bad_spec`."""


_BINOPS = {
    "+": lambda a, b: a + b,
    "-": lambda a, b: a - b,
    "*": lambda a, b: a * b,
    "/": lambda a, b: a / b,
    "%": lambda a, b: a % b,
    "=": lambda a, b: a == b,
    "==": lambda a, b: a == b,
    "!=": lambda a, b: a != b,
    "<": lambda a, b: a < b,
    "<=": lambda a, b: a <= b,
    ">": lambda a, b: a > b,
    ">=": lambda a, b: a >= b,
    "and": lambda a, b: a & b,
    "or": lambda a, b: a | b,
}

_UNOPS = {
    "not": lambda a: ~a,
    "neg": lambda a: -a,
    "abs": F.abs,
    "upper": F.upper,
    "lower": F.lower,
    "length": F.length,
}

_AGG_FNS = {
    "sum": F.sum, "count": F.count, "avg": F.avg, "mean": F.avg,
    "min": F.min, "max": F.max,
}


def compile_expr(node, params: Dict[str, object],
                 lit_factory=None) -> Column:
    """One expression node -> a Column, with `params` bound.

    `lit_factory(name, value) -> Column` is the plan cache's template
    hook: parameter references become ParamLiteral placeholders
    instead of plain literals, so the resolved tree is rebindable."""
    if not isinstance(node, dict):
        raise SpecError(f"expression must be an object, got {node!r}")
    if "col" in node:
        return F.col(str(node["col"]))
    if "lit" in node:
        return F.lit(node["lit"])
    if "param" in node:
        name = str(node["param"])
        if name not in params:
            raise SpecError(f"unbound parameter {name!r}; bound: "
                            f"{sorted(params)}")
        if lit_factory is not None:
            return lit_factory(name, params[name])
        return F.lit(params[name])
    if "fn" in node:
        fn = str(node["fn"])
        args = node.get("args", [])
        if not isinstance(args, list):
            raise SpecError(f"fn {fn!r} args must be a list")
        if fn == "isin":
            if len(args) < 2:
                raise SpecError("isin needs a column and >=1 value")
            if lit_factory is not None and \
                    any("param" in a for a in args[1:]
                        if isinstance(a, dict)):
                # isin value lists embed into the expression shape —
                # a rebindable template can't carry them (plan cache
                # treats the spec as uncacheable)
                raise SpecError("isin values cannot be parameters")
            vals = []
            for a in args[1:]:
                if not isinstance(a, dict):
                    raise SpecError(f"bad isin value: {a!r}")
                if "lit" in a:
                    vals.append(a["lit"])
                elif "param" in a and a["param"] in params:
                    vals.append(params[a["param"]])
                else:
                    raise SpecError(f"bad isin value: {a!r}")
            return compile_expr(args[0], params,
                                lit_factory).isin(*vals)
        cols = [compile_expr(a, params, lit_factory) for a in args]
        if fn in _BINOPS:
            if len(cols) != 2:
                raise SpecError(f"fn {fn!r} takes 2 args, got "
                                f"{len(cols)}")
            return _BINOPS[fn](cols[0], cols[1])
        if fn in _UNOPS:
            if len(cols) != 1:
                raise SpecError(f"fn {fn!r} takes 1 arg, got "
                                f"{len(cols)}")
            return _UNOPS[fn](cols[0])
        raise SpecError(f"unknown function {fn!r}")
    raise SpecError(f"unknown expression node: {sorted(node)}")


def _col_or_expr(c, params, lit_factory=None) -> Column:
    if isinstance(c, str):
        return F.col(c)
    return compile_expr(c, params, lit_factory)


def compile_spec(spec: dict, session, params: Dict[str, object],
                 lit_factory=None):
    """A relation spec -> DataFrame on `session` with params bound.

    Every compile-time failure surfaces as SpecError (wire code
    `bad_spec`) — including the plain ValueError/KeyError/TypeError
    that coercions and resolution raise — so engine faults raised
    AFTER a spec compiled are never misreported as spec errors."""
    try:
        return _compile_relation(spec, session, params, lit_factory)
    except SpecError:
        raise
    except (ValueError, KeyError, TypeError) as e:
        raise SpecError(f"spec failed to compile: {e}") from e


def _compile_relation(spec: dict, session, params: Dict[str, object],
                      lit_factory=None):
    if not isinstance(spec, dict) or "op" not in spec:
        raise SpecError("relation must be an object with an 'op'")
    op = spec["op"]

    def child(key="input"):
        if key not in spec:
            raise SpecError(f"op {op!r} requires {key!r}")
        return _compile_relation(spec[key], session, params,
                                 lit_factory)

    if op == "parquet":
        paths = spec.get("path")
        if isinstance(paths, str):
            paths = [paths]
        if not paths:
            raise SpecError("parquet op requires 'path'")
        return session.read.parquet(*[str(p) for p in paths])
    if op == "range":
        return session.range(int(spec.get("start", 0)),
                             int(spec["end"]),
                             int(spec.get("step", 1)))
    if op == "filter":
        return child().filter(
            compile_expr(spec["cond"], params, lit_factory))
    if op == "select":
        cols: List[Column] = []
        for c in spec.get("cols", []):
            if isinstance(c, str):
                cols.append(F.col(c))
            elif isinstance(c, dict) and "expr" in c:
                e = compile_expr(c["expr"], params, lit_factory)
                cols.append(e.alias(c["as"]) if "as" in c else e)
            else:
                raise SpecError(f"bad select column: {c!r}")
        if not cols:
            raise SpecError("select requires 'cols'")
        return child().select(*cols)
    if op == "agg":
        df = child()
        keys = [_col_or_expr(k, params, lit_factory)
                for k in spec.get("groupBy", [])]
        aggs = []
        for a in spec.get("aggs", []):
            fn = _AGG_FNS.get(str(a.get("fn")))
            if fn is None:
                raise SpecError(f"unknown agg fn {a.get('fn')!r}")
            arg = a.get("col", "*" if a.get("fn") == "count" else None)
            if arg is None:
                raise SpecError(f"agg {a.get('fn')!r} requires 'col'")
            c = fn(arg if isinstance(arg, str)
                   else compile_expr(arg, params, lit_factory))
            aggs.append(c.alias(a["as"]) if "as" in a else c)
        if not aggs:
            raise SpecError("agg requires 'aggs'")
        return df.groupBy(*keys).agg(*aggs)
    if op == "join":
        if "left" not in spec or "right" not in spec:
            raise SpecError("join requires 'left' and 'right'")
        left = _compile_relation(spec["left"], session, params,
                                 lit_factory)
        right = _compile_relation(spec["right"], session, params,
                                  lit_factory)
        on = spec.get("on")
        if not on:
            raise SpecError("join requires 'on' column names")
        return left.join(right, on=list(on),
                         how=str(spec.get("how", "inner")))
    if op == "orderBy":
        df = child()
        orders = []
        for k in spec.get("keys", []):
            if isinstance(k, str):
                orders.append(F.col(k).asc())
                continue
            c = (F.col(k["col"]) if "col" in k
                 else compile_expr(k["expr"], params, lit_factory))
            orders.append(c.asc() if k.get("asc", True) else c.desc())
        if not orders:
            raise SpecError("orderBy requires 'keys'")
        return df.orderBy(*orders)
    if op == "limit":
        return child().limit(int(spec["n"]))
    raise SpecError(f"unknown relation op {op!r}")
