"""Structural plan cache for served queries.

The compile cache (PR 1) made the EXECUTABLE warm; planning itself —
spec compilation, parquet schema inference off file footers, the
optimizer, physical overrides — was still paid per request. This
cache keys served queries the way the compile cache keys programs:
by a NORMALIZED structural digest with literals parameterized out.

Normalization rewrites every `{"lit": v}` in the spec to an
auto-parameter, so two requests that differ only in literal values
share one cache entry. The structural key is
  sha256(canonical spec JSON + tenant id + param type signature
         + planning-conf digest)
— tenant isolation is by construction (tenant A's entries can never
serve tenant B), and any `spark.*` conf change (a different
fusedExec/mesh/admission planning posture) changes the digest and
misses cleanly instead of serving a stale plan.

Each entry caches the fully RESOLVED logical template (built once,
with ParamLiteral placeholders) plus an LRU of fully planned physical
plans per distinct parameter binding:

- exact-binding repeat -> checkout of the planned physical: skips
  spec compile, schema inference, optimize and plan_query outright,
  and rides the warm compiled executables (`hit` / `hitsExact`).
- new binding on a known shape -> ParamLiteral substitution into the
  template then optimize+plan_query only (`hit` / `hitsRebind`):
  re-planning is REQUIRED for correctness — literal values flow into
  pushed-down parquet predicates and compiled-program keys — but the
  serving front-end (spec walk + footer reads + resolution) is
  skipped.
- unknown shape -> full build (`miss`).

Physical plans check OUT exclusively: two concurrent requests on the
same binding never share one physical tree mid-execution (the second
re-plans from the template); a failed execution drops its binding so
a poisoned plan is never served twice.
"""

from __future__ import annotations

import hashlib
import json
import threading
from collections import OrderedDict
from typing import Dict, Optional, Tuple

from spark_rapids_tpu.api.dataframe import DataFrame
from spark_rapids_tpu.expr.core import Literal
from spark_rapids_tpu.serve.spec import SpecError

#: Auto-extracted literals bind under this RESERVED name prefix.
#: Client-supplied params (and `{"param": ...}` references in specs)
#: may not use it — otherwise a request param could silently shadow a
#: spec literal's value and diverge from the cache-disabled path.
AUTO_PARAM_PREFIX = "__lit"


class ParamLiteral(Literal):
    """A literal placeholder in a cached logical template, carrying
    the parameter name it binds. Never executed — binding substitutes
    a plain Literal before optimize/plan_query."""

    def __init__(self, name: str, value, dtype=None):
        super().__init__(value, dtype)
        self.param_name = name


class _PrebuiltDataFrame(DataFrame):
    """A DataFrame whose physical plan was already built (checkout
    from the cache): `_physical()` returns it instead of re-planning.
    The cpu_oracle path still plans fresh from the logical tree — the
    oracle must never see a cached device plan."""

    def __init__(self, plan, session, prebuilt):
        super().__init__(plan, session)
        self._prebuilt = prebuilt

    def _physical(self, cpu_oracle: bool = False):
        if cpu_oracle or self._prebuilt is None:
            return super()._physical(cpu_oracle)
        return self._prebuilt


class _CapturingDataFrame(DataFrame):
    """A DataFrame that remembers the physical plan its collect built,
    so the cache can store it for the next exact-binding repeat
    without planning a second time."""

    def __init__(self, plan, session):
        super().__init__(plan, session)
        self._built = None

    def _physical(self, cpu_oracle: bool = False):
        out = super()._physical(cpu_oracle)
        if not cpu_oracle:
            self._built = out
        return out


def normalize_spec(spec) -> Tuple[dict, Dict[str, object]]:
    """Parameterize literals out: every `{"lit": v}` becomes
    `{"param": "__litN"}` (N in deterministic walk order, under the
    reserved AUTO_PARAM_PREFIX), returning the normalized spec and
    the extracted auto-bindings. A spec referencing the reserved
    prefix itself is rejected (it would collide with an extracted
    literal). `isin` value lists stay verbatim — their arity and
    values are part of the expression SHAPE (a different list is a
    different plan), so they key structurally instead of
    parameterizing."""
    auto: Dict[str, object] = {}

    def walk(node):
        if isinstance(node, dict):
            if node.get("fn") == "isin" and \
                    isinstance(node.get("args"), list) and node["args"]:
                return {**node,
                        "args": [walk(node["args"][0])]
                        + list(node["args"][1:])}
            if set(node) == {"lit"} or (set(node) == {"lit", "type"}):
                name = f"{AUTO_PARAM_PREFIX}{len(auto)}"
                auto[name] = node["lit"]
                return {"param": name}
            if "param" in node and \
                    str(node["param"]).startswith(AUTO_PARAM_PREFIX):
                raise SpecError(
                    f"parameter name {node['param']!r} uses the "
                    f"reserved {AUTO_PARAM_PREFIX!r} prefix (held for "
                    f"auto-extracted literals)")
            return {k: walk(v) for k, v in node.items()}
        if isinstance(node, list):
            return [walk(v) for v in node]
        return node

    return walk(spec), auto


def _digest(obj) -> str:
    return hashlib.sha256(
        json.dumps(obj, sort_keys=True, default=repr).encode()
    ).hexdigest()


def conf_digest(settings: dict) -> str:
    """Digest of every `spark.*` setting — planning posture; any
    change invalidates (misses) rather than risking a stale plan."""
    return _digest(sorted(
        (k, repr(v)) for k, v in settings.items()
        if str(k).startswith("spark.")))


def binding_key(params: Dict[str, object]) -> str:
    return _digest(sorted(
        (k, type(v).__name__, repr(v)) for k, v in params.items()))


def type_signature(params: Dict[str, object]) -> list:
    return sorted((k, type(v).__name__) for k, v in params.items())


def affinity_key(tenant: str, spec, params: Optional[dict] = None
                 ) -> str:
    """The fleet router's hash-ring input: the structural identity of
    a request WITHOUT the per-replica planning conf (replicas may run
    different confs) and WITHOUT literal binding values (repeat shapes
    with different literals should land on the replica whose plan
    cache already holds the shape's template). Byte-stable across
    processes and sessions — it is normalize_spec + _digest over
    canonical JSON, nothing machine-local — which is what makes
    router-side affinity line up with replica-side structural keys."""
    norm_spec, auto = normalize_spec(spec)
    bound = {**auto, **(params or {})}
    return _digest({"spec": norm_spec, "tenant": tenant,
                    "types": type_signature(bound)})


class _Binding:
    __slots__ = ("phys", "meta", "logical", "in_use")

    def __init__(self, logical, phys, meta):
        self.logical = logical
        self.phys = phys
        self.meta = meta
        self.in_use = False


class _Entry:
    __slots__ = ("template", "bindings")

    def __init__(self, template):
        self.template = template  # resolved logical w/ ParamLiterals
        self.bindings: "OrderedDict[str, _Binding]" = OrderedDict()


class PlanCacheStats:
    _FIELDS = ("hits", "hitsExact", "hitsRebind", "misses",
               "evictions")

    def __init__(self):
        self._lock = threading.Lock()
        self._v = {f: 0 for f in self._FIELDS}

    def add(self, field: str, n: int = 1) -> None:
        with self._lock:
            self._v[field] += n

    def snapshot(self) -> dict:
        with self._lock:
            out = dict(self._v)
        looked = out["hits"] + out["misses"]
        out["hitRatio"] = round(out["hits"] / looked, 4) if looked \
            else 0.0
        return out


class PlanCache:
    """Bounded structural plan cache (LRU entries, LRU bindings)."""

    def __init__(self, max_entries: int = 256,
                 bindings_per_entry: int = 16, enabled: bool = True):
        self.enabled = enabled
        self.max_entries = max(1, int(max_entries))
        self.bindings_per_entry = max(1, int(bindings_per_entry))
        self.stats = PlanCacheStats()
        self._lock = threading.Lock()
        self._entries: "OrderedDict[str, _Entry]" = OrderedDict()

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def clear(self) -> None:
        with self._lock:
            self._entries.clear()

    # --- keying ---

    def structural_key(self, tenant: str, norm_spec: dict,
                       params: Dict[str, object],
                       settings: dict) -> str:
        return _digest({"spec": norm_spec, "tenant": tenant,
                        "types": type_signature(params),
                        "conf": conf_digest(settings)})

    # --- the serve-path entry point ---

    def dataframe_for(self, session, tenant: str, spec: dict,
                      params: Optional[Dict[str, object]] = None):
        """Resolve `spec` + `params` to an executable DataFrame.

        Returns (df, info, release): `release(success)` MUST be called
        after execution — it checks a borrowed physical back in (or
        stores/drops a fresh one). `info` carries the cache verdict
        ("hit-exact" | "hit-rebind" | "miss" | "bypass") and key
        digests for diagnostics."""
        from spark_rapids_tpu.plan import logical as L
        from spark_rapids_tpu.serve.spec import compile_spec

        bad = sorted(k for k in (params or {})
                     if str(k).startswith(AUTO_PARAM_PREFIX))
        if bad:
            raise SpecError(
                f"parameter names {bad} use the reserved "
                f"{AUTO_PARAM_PREFIX!r} prefix (held for "
                f"auto-extracted literals); rename them")
        norm_spec, auto = normalize_spec(spec)
        bound = {**auto, **(params or {})}
        if not self.enabled:
            df = compile_spec(spec, session, bound)
            return df, {"planCache": "bypass"}, lambda _ok: None
        skey = self.structural_key(tenant, norm_spec, bound,
                                   session._settings)
        bkey = binding_key(bound)
        info = {"planCache": "miss", "key": skey[:12]}

        with self._lock:
            entry = self._entries.get(skey)
            if entry is not None:
                self._entries.move_to_end(skey)
                b = entry.bindings.get(bkey)
                if b is not None and not b.in_use:
                    # exact repeat: the planned physical checks out
                    b.in_use = True
                    entry.bindings.move_to_end(bkey)
                    self.stats.add("hits")
                    self.stats.add("hitsExact")
                    info["planCache"] = "hit-exact"
                    df = _PrebuiltDataFrame(b.logical, session,
                                            (b.phys, b.meta))
                    return df, info, self._releaser(skey, bkey, b)
                template = entry.template
            else:
                template = None

        if template is not None:
            # known shape, new (or busy) binding: substitute the
            # params into the resolved template — no spec walk, no
            # schema inference — then re-plan physically
            def bind(e):
                def sub(node):
                    if isinstance(node, ParamLiteral):
                        return Literal(bound[node.param_name])
                    return node
                return e.transform(sub)

            plan = L.transform_expressions(template, bind)
            self.stats.add("hits")
            self.stats.add("hitsRebind")
            info["planCache"] = "hit-rebind"
            df = _CapturingDataFrame(plan, session)
            return df, info, self._storer(skey, bkey, df)

        # unknown shape: full build, and ALSO keep the ParamLiteral
        # template so the next binding skips the front-end
        self.stats.add("misses")
        try:
            template = self._build_template(session, norm_spec, bound)
        except SpecError:
            # uncacheable construct (e.g. a parameter inside an isin
            # value list): serve it directly, cache nothing — a
            # genuinely bad spec raises the same error right here
            df = compile_spec(spec, session, bound)
            return df, info, lambda ok=True: None

        def bind_first(e):
            def sub(node):
                if isinstance(node, ParamLiteral):
                    return Literal(bound[node.param_name])
                return node
            return e.transform(sub)

        df = _CapturingDataFrame(
            L.transform_expressions(template, bind_first), session)
        with self._lock:
            if skey not in self._entries:
                self._entries[skey] = _Entry(template)
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
                    self.stats.add("evictions")
        return df, info, self._storer(skey, bkey, df)

    # --- internals ---

    def _build_template(self, session, norm_spec: dict,
                        bound: Dict[str, object]):
        """Compile the normalized spec once with ParamLiteral
        placeholders (carrying real current values, so resolution
        sees honest dtypes) and keep the resolved logical tree."""
        from spark_rapids_tpu.api.column import Column
        from spark_rapids_tpu.serve.spec import compile_spec

        def lit_factory(name, value):
            return Column(ParamLiteral(name, value))

        df = compile_spec(norm_spec, session, bound,
                          lit_factory=lit_factory)
        return df._plan

    def _releaser(self, skey: str, bkey: str, binding: _Binding):
        def release(success: bool = True) -> None:
            with self._lock:
                binding.in_use = False
                if not success:
                    entry = self._entries.get(skey)
                    if entry is not None:
                        entry.bindings.pop(bkey, None)
        return release

    def _storer(self, skey: str, bkey: str, df: "_CapturingDataFrame"):
        """After a miss/rebind executes OK, store the physical plan
        its collect built for the next exact-binding repeat."""
        def release(success: bool = True) -> None:
            built = df._built
            if not success or built is None:
                return
            phys, meta = built
            with self._lock:
                entry = self._entries.get(skey)
                if entry is None:
                    return
                entry.bindings[bkey] = _Binding(df._plan, phys, meta)
                entry.bindings.move_to_end(bkey)
                while len(entry.bindings) > self.bindings_per_entry:
                    entry.bindings.popitem(last=False)
                    self.stats.add("evictions")
        return release
